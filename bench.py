"""Benchmark: CIND-candidate-pairs checked per second per chip.

Workload: synthetic RDF (LUBM/DBpedia-shaped, utils/synth.py), full AllAtOnce
discovery incl. binary captures at min_support=10 — BASELINE.md config-1 analog.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is measured in-process against the single-core pure-Python oracle
(rdfind_tpu.oracle.discover_cinds_joinline) on a subsample, scaled to pairs/sec —
the honest stand-in for the reference's single-worker throughput, since the repo
ships no Flink cluster numbers (BASELINE.md: "published: none in repo").

Resilience: the measurement machinery must always report, like the reference's
AbstractFlinkProgram.java:65-77,175-182 (per-plan timing printed no matter what).
Backend init is retried; on persistent TPU failure we fall back to local CPU and
record the backend used; any unrecoverable error still prints a diagnostic JSON
line (never a bare traceback) with value=0 so the driver can parse something.
"""

import json
import os
import sys
import time
import traceback


def _probe_tpu_subprocess(timeout_s: int) -> tuple[bool, str]:
    """Probe the default (TPU) backend in a subprocess with a hard timeout.

    A hung tunnel blocks inside a C call, so no in-process watchdog (SIGALRM)
    can interrupt it — only a killable subprocess gives a reliable verdict.
    """
    import subprocess

    code = ("import jax, jax.numpy as jnp;"
            "d = jax.devices();"
            "jax.block_until_ready(jnp.zeros((8,), jnp.int32) + 1);"
            "print(d[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s}s"
    if r.returncode == 0:
        return True, r.stdout.strip().splitlines()[-1]
    tail = (r.stderr or "").strip().splitlines()
    return False, tail[-1] if tail else f"probe rc={r.returncode}"


FALLBACK_REASON = None  # set when _init_backend had to abandon the TPU


def _init_backend(retries: int = 2, delay_s: float = 5.0,
                  attempt_timeout_s: int = 120) -> str:
    """Initialize a usable jax backend, preferring the TPU; return its name.

    The axon TPU tunnel can fail transiently ("Unable to initialize backend
    'axon'", round-1 BENCH rc=1) or hang outright; probe it in a killable
    subprocess, retry, then fall back to the local CPU backend so the bench
    still produces a number (flagged via the backend field).  BENCH_BACKEND=cpu
    pins CPU outright — note env JAX_PLATFORMS alone is NOT enough in this
    image (sitecustomize force-sets the config), jax.config.update after
    import is required.
    """
    import jax

    forced = os.environ.get("BENCH_BACKEND")
    if forced:
        jax.config.update("jax_platforms", forced)
        return jax.devices()[0].platform

    last_err = None
    for attempt in range(retries):
        ok, info = _probe_tpu_subprocess(attempt_timeout_s)
        if ok:
            return jax.devices()[0].platform
        last_err = info
        time.sleep(delay_s * (attempt + 1))
    # Persistent TPU failure: pin to CPU before any in-process jax op.
    global FALLBACK_REASON
    FALLBACK_REASON = (f"TPU backend unavailable after {retries} probes "
                       f"({last_err})")
    sys.stderr.write(f"bench: {FALLBACK_REASON}; falling back to cpu\n")
    jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform


def _run(n: int, min_support: int) -> dict:
    backend = _init_backend()

    from rdfind_tpu import oracle
    from rdfind_tpu.models import allatonce
    from rdfind_tpu.utils.synth import generate_triples

    triples = generate_triples(n, seed=42)

    # Warm-up (compile cache) on the same shapes, then measure.
    stats = {}
    allatonce.discover(triples, min_support, stats=stats)
    t0 = time.perf_counter()
    table = allatonce.discover(triples, min_support, stats=stats)
    elapsed = time.perf_counter() - t0
    pairs_per_sec = stats["total_pairs"] / elapsed

    # Oracle baseline: the single-core pure-Python joinline oracle on the SAME
    # workload (like-for-like; the r2 subsample extrapolation understated the
    # oracle's superlinear pair load).  ~15 s at the 200k default.
    all_t = [tuple(int(x) for x in row) for row in triples]
    t0 = time.perf_counter()
    oracle.discover_cinds_joinline(all_t, min_support)
    oracle_elapsed = time.perf_counter() - t0
    oracle_pairs_per_sec = stats["total_pairs"] / oracle_elapsed

    detail = {
        "backend": backend,
        **({} if FALLBACK_REASON is None else {
            "backend_note": FALLBACK_REASON + "; CPU fallback — see "
                            "BASELINE.md for the measured real-chip headline"}),
        "n_triples": n, "min_support": min_support,
        "wall_s": round(elapsed, 3), "total_pairs": stats["total_pairs"],
        "n_lines": stats["n_lines"], "max_line": stats["max_line"],
        "cinds": len(table),
        "pair_backend": stats.get("pair_backend"),
        "oracle_wall_s": round(oracle_elapsed, 3),
        "oracle_pairs_per_sec": round(oracle_pairs_per_sec, 1),
    }

    # The DEFAULT strategy (SmallToLarge, id 1) on the same workload, so the
    # default path always has a recorded number too (best-effort).
    try:
        from rdfind_tpu.models import small_to_large
        s2l_stats: dict = {}
        small_to_large.discover(triples, min_support, stats=s2l_stats)  # warm
        s2l_stats.clear()
        t0 = time.perf_counter()
        s2l_table = small_to_large.discover(triples, min_support,
                                            stats=s2l_stats)
        s2l_wall = time.perf_counter() - t0
        detail["s2l"] = {
            "wall_s": round(s2l_wall, 3),
            "total_pairs": int(s2l_stats.get("total_pairs", 0)),
            "pairs_per_sec": round(
                s2l_stats.get("total_pairs", 0) / s2l_wall, 1),
            "cinds": len(s2l_table),
        }
    except Exception as e:
        detail["s2l"] = {"error": f"{type(e).__name__}: {e}"}

    # Pallas packed-bitset kernel vs jnp planes path, on this backend.
    try:
        from rdfind_tpu.ops import sketch
        pk = sketch.kernel_selfcheck(n_rows=1024, n_bits=4096,
                                     backend=backend)
        detail["pallas_vs_jnp"] = pk
    except Exception as e:  # kernel comparison is best-effort
        detail["pallas_vs_jnp"] = {"error": f"{type(e).__name__}: {e}"}

    return {
        "metric": "cind_pairs_checked_per_sec_per_chip",
        "value": round(pairs_per_sec, 1),
        "unit": "pairs/s",
        "vs_baseline": round(pairs_per_sec / oracle_pairs_per_sec, 3),
        "detail": detail,
    }


def main():
    n = int(os.environ.get("BENCH_TRIPLES", 200_000))
    min_support = int(os.environ.get("BENCH_MIN_SUPPORT", 10))
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        result = _run(n, min_support)
    except Exception as e:
        tb = traceback.format_exc(limit=3)
        result = {
            "metric": "cind_pairs_checked_per_sec_per_chip",
            "value": 0,
            "unit": "pairs/s",
            "vs_baseline": 0,
            "detail": {"error": f"{type(e).__name__}: {e}",
                       "traceback": tb.splitlines()[-3:]},
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
