"""Benchmark: CIND-candidate-pairs checked per second per chip.

Workload: synthetic RDF (LUBM/DBpedia-shaped, utils/synth.py), full AllAtOnce
discovery incl. binary captures at min_support=10 — BASELINE.md config-1 analog.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is measured in-process against the single-core pure-Python oracle
(rdfind_tpu.oracle.discover_cinds_joinline) on a subsample, scaled to pairs/sec —
the honest stand-in for the reference's single-worker throughput, since the repo
ships no Flink cluster numbers (BASELINE.md: "published: none in repo").
"""

import json
import os
import sys
import time


def main():
    n = int(os.environ.get("BENCH_TRIPLES", 200_000))
    min_support = int(os.environ.get("BENCH_MIN_SUPPORT", 10))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from rdfind_tpu import oracle
    from rdfind_tpu.models import allatonce
    from rdfind_tpu.utils.synth import generate_triples

    triples = generate_triples(n, seed=42)

    # Warm-up (compile cache) on the same shapes, then measure.
    stats = {}
    allatonce.discover(triples, min_support, stats=stats)
    t0 = time.perf_counter()
    table = allatonce.discover(triples, min_support, stats=stats)
    elapsed = time.perf_counter() - t0
    pairs_per_sec = stats["total_pairs"] / elapsed

    # Oracle baseline on a subsample (python dict-of-sets single core).
    n_sub = min(n, 20_000)
    sub = triples[:n_sub]
    sub_t = [tuple(int(x) for x in row) for row in sub]
    t0 = time.perf_counter()
    oracle.discover_cinds_joinline(sub_t, min_support)
    oracle_elapsed = time.perf_counter() - t0
    sub_stats = {}
    allatonce.discover(sub, min_support, stats=sub_stats)
    oracle_pairs_per_sec = sub_stats["total_pairs"] / oracle_elapsed

    print(json.dumps({
        "metric": "cind_pairs_checked_per_sec_per_chip",
        "value": round(pairs_per_sec, 1),
        "unit": "pairs/s",
        "vs_baseline": round(pairs_per_sec / oracle_pairs_per_sec, 3),
        "detail": {
            "n_triples": n, "min_support": min_support,
            "wall_s": round(elapsed, 3), "total_pairs": stats["total_pairs"],
            "n_lines": stats["n_lines"], "max_line": stats["max_line"],
            "cinds": len(table),
            "oracle_pairs_per_sec": round(oracle_pairs_per_sec, 1),
        },
    }))


if __name__ == "__main__":
    main()
