"""Benchmark: CIND-candidate-pairs checked per second per chip.

Workload: synthetic RDF (LUBM/DBpedia-shaped, utils/synth.py), full AllAtOnce
discovery incl. binary captures at min_support=10 — BASELINE.md config-1 analog.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is measured in-process against the single-core pure-Python oracle
(rdfind_tpu.oracle.discover_cinds_joinline) on a subsample, scaled to pairs/sec —
the honest stand-in for the reference's single-worker throughput, since the repo
ships no Flink cluster numbers (BASELINE.md: "published: none in repo").

Resilience: the measurement machinery must always report, like the reference's
AbstractFlinkProgram.java:65-77,175-182 (per-plan timing printed no matter what).
Backend init is retried; on persistent TPU failure we fall back to local CPU and
record the backend used; any unrecoverable error still prints a diagnostic JSON
line (never a bare traceback) with value=0 so the driver can parse something.
"""

import json
import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from rdfind_tpu import obs  # noqa: E402
from rdfind_tpu.obs import integrity as obs_integrity  # noqa: E402
from rdfind_tpu.obs import report as obs_report  # noqa: E402
from rdfind_tpu.obs import sentinel as obs_sentinel  # noqa: E402


def _probe_tpu_subprocess(timeout_s: int) -> tuple[bool, str]:
    """Probe the default (TPU) backend in a subprocess with a hard timeout.

    A hung tunnel blocks inside a C call, so no in-process watchdog (SIGALRM)
    can interrupt it — only a killable subprocess gives a reliable verdict.
    """
    import subprocess

    code = ("import jax, jax.numpy as jnp;"
            "d = jax.devices();"
            "jax.block_until_ready(jnp.zeros((8,), jnp.int32) + 1);"
            "print(d[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s}s"
    if r.returncode == 0:
        return True, r.stdout.strip().splitlines()[-1]
    tail = (r.stderr or "").strip().splitlines()
    return False, tail[-1] if tail else f"probe rc={r.returncode}"


FALLBACK_REASON = None  # set when _init_backend had to abandon the TPU


def _init_backend(retries: int = 2, delay_s: float = 5.0,
                  attempt_timeout_s: int = 120) -> str:
    """Initialize a usable jax backend, preferring the TPU; return its name.

    The axon TPU tunnel can fail transiently ("Unable to initialize backend
    'axon'", round-1 BENCH rc=1) or hang outright; probe it in a killable
    subprocess, retry, then fall back to the local CPU backend so the bench
    still produces a number (flagged via the backend field).  BENCH_BACKEND=cpu
    pins CPU outright — note env JAX_PLATFORMS alone is NOT enough in this
    image (sitecustomize force-sets the config), jax.config.update after
    import is required.
    """
    import jax

    forced = os.environ.get("BENCH_BACKEND")
    if forced:
        jax.config.update("jax_platforms", forced)
        return jax.devices()[0].platform

    last_err = None
    for attempt in range(retries):
        ok, info = _probe_tpu_subprocess(attempt_timeout_s)
        if ok:
            return jax.devices()[0].platform
        last_err = info
        time.sleep(delay_s * (attempt + 1))
    # Persistent TPU failure: pin to CPU before any in-process jax op.
    global FALLBACK_REASON
    FALLBACK_REASON = (f"TPU backend unavailable after {retries} probes "
                       f"({last_err})")
    sys.stderr.write(f"bench: {FALLBACK_REASON}; falling back to cpu\n")
    jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform


# Public per-chip spec-sheet peaks (cloud.google.com/tpu docs): the roofline
# denominators for the MFU report.
TPU_PEAKS = {
    "v5e": {"bf16_tflops": 197.0, "int8_tops": 394.0, "hbm_gbps": 819.0},
    "v5p": {"bf16_tflops": 459.0, "int8_tops": 918.0, "hbm_gbps": 2765.0},
    "v4": {"bf16_tflops": 275.0, "hbm_gbps": 1228.0},
}


def _measure_mfu(stats: dict, backend: str) -> dict:
    """Achieved FLOP/s of the dense cooc matmul at this workload's shapes.

    Times the device-only scheduled tile sweep (the jitted cooc_cind_tile, no
    host unpack) on the same DensePlan the bench workload used.  Reports BOTH
    raw MFU (issued FLOPs / time / peak — the MXU's utilization on the work
    actually dispatched, padding included) and occupancy-corrected MFU
    (real FLOPs / time / peak = raw * plan occupancy — the fraction of peak
    spent on the unpadded workload, the honest headline).  Fraction-of-peak
    needs a TPU (chip generation from PALLAS_AXON_TPU_GEN); raw FLOP/s and
    the occupancy record are reported everywhere.
    """
    import jax
    import jax.numpy as jnp

    from rdfind_tpu.ops import cooc

    plan = cooc.dense_plan(stats.get("n_lines", 0),
                           stats.get("n_captures", 0))
    if plan is None:
        return {"error": "dense plan does not apply at this workload"}
    l_pad, c_pad, tile = plan.l_pad, plan.c_pad, plan.tile

    rng = np.random.default_rng(5)
    member_h = rng.random((l_pad, c_pad)) < 0.01
    dep_count = jnp.asarray(rng.integers(1, 50, c_pad, np.int32))
    cap_id = jnp.asarray(rng.integers(0, 1 << 20, c_pad, np.int32))

    def time_sweep(dtype):
        # One dtype's matrix lives on device at a time: an int8-sized plan
        # can admit shapes whose bf16 matrix alone busts HBM, so each sweep
        # materializes (and frees) its own matrix and is guarded separately.
        mat = jnp.asarray(member_h, dtype)

        def sweep():
            outs = [cooc.cooc_cind_tile(mat, jnp.int32(lo), dep_count, cap_id,
                                        cap_id, cap_id, jnp.int32(10),
                                        tile=tile)
                    for lo in plan.dep_tile_starts]
            jax.block_until_ready(outs)

        sweep()  # compile
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            sweep()
        return (time.perf_counter() - t0) / reps

    issued = float(plan.issued_flops)
    out = {"plan": plan.describe(), "l_pad": l_pad, "c_pad": c_pad,
           "tile": tile, "occupancy": round(plan.occupancy, 4)}
    achieved = None
    try:
        dt = time_sweep(jnp.bfloat16)
        achieved = issued / dt
        out["sweep_s"] = round(dt, 4)
        out["achieved_tflops"] = round(achieved / 1e12, 3)
    except Exception as e:  # e.g. bf16 matrix over HBM under an int8 plan
        out["bf16_error"] = f"{type(e).__name__}: {e}"
    achieved8 = None
    try:
        # Same sweep on int8 membership (the default cooc dtype on int8-MXU
        # backends): measures the int8 path at these shapes.
        dt8 = time_sweep(jnp.int8)
        achieved8 = issued / dt8
        out["int8_achieved_tops"] = round(achieved8 / 1e12, 3)
        if achieved is not None:
            out["int8_vs_bf16"] = round(dt / dt8, 3)
    except Exception as e:  # int8 matmul unsupported on some backends
        out["int8_error"] = f"{type(e).__name__}: {e}"
    if backend == "tpu" and cooc.fuse_verdict_enabled():
        # The fused-verdict kernel at the same shapes (device-only, full
        # K-block schedule): the raw-roofline row the headline run rides.
        try:
            kl = plan.line_block
            nb = l_pad // kl
            bids = jnp.asarray(np.arange(nb, dtype=np.int32))
            nr = jnp.asarray(np.full(1, nb, np.int32))
            mat = jnp.asarray(member_h,
                              jnp.int8 if plan.dtype == "int8"
                              else jnp.bfloat16)

            def fsweep():
                outs = [cooc._fused_cind_tile(
                    mat, jnp.int32(lo), dep_count, cap_id, cap_id, cap_id,
                    jnp.int32(10), bids, nr, tile=tile, interpret=False)
                    for lo in plan.dep_tile_starts]
                jax.block_until_ready(outs)

            fsweep()  # compile
            reps = 3
            t0 = time.perf_counter()
            for _ in range(reps):
                fsweep()
            dtf = (time.perf_counter() - t0) / reps
            out["fused_sweep_s"] = round(dtf, 4)
            out["fused_achieved_tflops"] = round(issued / dtf / 1e12, 3)
            # Against the unfused sweep of the SAME resolved dtype.
            base = achieved8 if (plan.dtype == "int8" and achieved8) \
                else achieved
            if base:
                out["fused_vs_unfused"] = round((issued / base) / dtf, 3)
        except Exception as e:
            out["fused_error"] = f"{type(e).__name__}: {e}"
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    if backend == "tpu" and gen in TPU_PEAKS:
        peaks = TPU_PEAKS[gen]
        peak = peaks["bf16_tflops"] * 1e12
        out["chip"] = gen
        out["peak_bf16_tflops"] = peaks["bf16_tflops"]
        if achieved is not None:
            out["bf16_mfu"] = round(achieved / peak, 4)
        if achieved8 is not None and "int8_tops" in peaks:
            out["int8_mfu"] = round(
                achieved8 / (peaks["int8_tops"] * 1e12), 4)
            out["int8_mfu_corrected"] = round(
                out["int8_mfu"] * plan.occupancy, 4)
        # The HEADLINE mfu follows the *resolved* membership dtype, against
        # the matching MXU peak (an int8 run rated against the bf16 peak
        # would understate utilization 2x; int4 nibble planes keep the int8
        # membership element, so int8 is also their honest denominator) —
        # labeled so the artifact says which roofline it is.
        resolved = cooc.resolved_cooc_dtype()
        if resolved == "int8" and achieved8 is not None \
                and "int8_tops" in peaks:
            out["peak_dtype"] = "int8"
            out["peak_tflops"] = peaks["int8_tops"]
            head = achieved8 / (peaks["int8_tops"] * 1e12)
        elif achieved is not None:
            out["peak_dtype"] = "bf16"
            out["peak_tflops"] = peaks["bf16_tflops"]
            head = achieved / peak
        else:
            head = None
        if head is not None:
            out["mfu"] = round(head, 4)
            out["mfu_corrected"] = round(head * plan.occupancy, 4)
    return out


def _bench_kernel_modes(backend: str) -> dict:
    """Per-mode rows for the containment/CIND kernels: plane bits x fused
    verdict, each with an HBM watermark sample (obs/memory.py), so the
    "fused never materializes the cooc counts" claim is a measured number.

    Plane rows rerun the packed-containment selfcheck across the full
    rung-3 grid: {8,4,2}-bit planes x emit_pipeline off/on (sub-byte rows
    only lower natively where the backend probe says so — elsewhere they
    record the emulated parity run; the emit=on row off-TPU records the
    probe refusing and the materialized path running, which is the
    fallback contract under test).  Fused rows run the same dense CIND
    sweep with RDFIND_FUSE_VERDICT off/on, fused FIRST, so a higher HBM
    peak on the materialized row is attributable to the int32 cooc tile
    the fused kernel keeps in VMEM.  On backends without memory stats
    (CPU) the hbm field is None and, off-TPU, the fused row shrinks to a
    tiny interpreted parity check.
    """
    import jax
    import jax.numpy as jnp

    from rdfind_tpu.obs import memory
    from rdfind_tpu.ops import cooc, sketch

    on_tpu = backend == "tpu"
    # by_mode mirrors the rows keyed by mode name: the sentinel's _dig walks
    # dicts only, so per-mode walls are only trackable through this view.
    out = {"modes": [], "by_mode": {}}

    def hbm():
        rec = memory.sample(None, publish=False)
        return None if rec is None else {
            "in_use_bytes": rec["in_use_bytes"],
            "peak_bytes": rec["peak_bytes"],
            "delta_bytes": rec["delta_bytes"]}

    saved_pb, saved_fv = cooc.PLANE_BITS, cooc.FUSE_VERDICT
    saved_em = cooc.EMIT_PIPELINE
    try:
        baseline_hashes: dict = {}
        for pb in ("8", "4", "2"):
            for em in ("0", "1"):
                cooc.PLANE_BITS = pb
                cooc.EMIT_PIPELINE = em
                row = {"mode": f"planes{pb}" + ("-emit" if em == "1" else ""),
                       "kernel_dtype": cooc.resolved_kernel_dtype(),
                       "emit_requested": em == "1"}
                try:
                    n = 2048 if on_tpu else 256
                    row.update(sketch.kernel_selfcheck(
                        n_rows=n, n_bits=4096, backend=backend, repeats=3))
                    # All six rows run the identical logical contraction:
                    # the paired emit row must reproduce its non-emit
                    # sibling bit-for-bit (off-TPU the probe refuses and
                    # the emit row IS the materialized path — the check
                    # then proves the fallback contract instead).
                    if "out_hash" in row:
                        if pb in baseline_hashes:
                            row["outputs_identical"] = (
                                row["out_hash"] == baseline_hashes[pb])
                        else:
                            baseline_hashes[pb] = row["out_hash"]
                except Exception as e:
                    row["error"] = f"{type(e).__name__}: {e}"
                row["hbm"] = hbm()
                out["modes"].append(row)
                out["by_mode"][row["mode"]] = row
        cooc.PLANE_BITS = saved_pb
        cooc.EMIT_PIPELINE = saved_em

        # Fused-verdict rows share one membership matrix; the sweep is the
        # full scheduled dep-tile pass of discover_pairs_dense.
        rng = np.random.default_rng(7)
        n_lines, num_caps = (100_000, 4096) if on_tpu else (300, 200)
        plan = cooc.dense_plan(n_lines, num_caps)
        if plan is None:
            out["fused_error"] = "dense plan does not fit"
            return out
        member = rng.random((plan.l_pad, plan.c_pad)) < 0.01
        dt = jnp.int8 if plan.dtype == "int8" else jnp.bfloat16
        m = jax.block_until_ready(jnp.asarray(member, dt))
        dep_count = member.sum(axis=0).astype(np.int64)
        cap_id = rng.integers(0, 1 << 20, plan.c_pad).astype(np.int64)
        baseline = None
        for fv in ("1", "0"):  # fused first: see docstring
            cooc.FUSE_VERDICT = fv
            mode_plan = cooc.dense_plan(n_lines, num_caps)
            stats: dict = {}
            t0 = time.perf_counter()
            d, r, _ = cooc.discover_pairs_dense(
                m, dep_count, cap_id, cap_id, cap_id, 10,
                num_caps, mode_plan.tile, starts=mode_plan.dep_tile_starts,
                plan=mode_plan, stats=stats)
            wall = time.perf_counter() - t0
            pairs = set(zip(d.tolist(), r.tolist()))
            row = {"mode": "fused" if fv == "1" else "materialized",
                   "wall_s": round(wall, 4),
                   "n_cinds": len(pairs),
                   "n_blocks_skipped": stats.get("n_blocks_skipped"),
                   "hbm": hbm()}
            if baseline is None:
                baseline = pairs
            else:
                row["outputs_identical"] = pairs == baseline
            out["modes"].append(row)
            out["by_mode"][row["mode"]] = row
    finally:
        cooc.PLANE_BITS, cooc.FUSE_VERDICT = saved_pb, saved_fv
        cooc.EMIT_PIPELINE = saved_em
    return out


def _bench_pipelined_passes(min_support: int) -> dict:
    """Multi-pass dispatch proxy: the sharded streaming pair phase under a
    tiny RDFIND_PAIR_ROW_BUDGET (n_pass >= 4 on this workload), pipelined vs
    RDFIND_SYNC_PASSES=1.  Records the dispatch counters (host syncs, sync
    time, overlapped pull time, in-flight depth, cap retries) so the JSON
    artifact PROVES the compute/readback overlap rather than asserting it;
    outputs of the two modes are asserted identical in-process.
    """
    from rdfind_tpu.models import sharded
    from rdfind_tpu.parallel.mesh import make_mesh
    from rdfind_tpu.utils.synth import generate_triples

    # Sized for the CPU fallback (one core proxying the whole mesh): big
    # enough for n_pass >= 4 under the adaptive budget below, small enough
    # that 5 pipeline runs (probe + 2x warm/timed) stay in low minutes.
    # On the real chip, raise BENCH_PIPELINE_TRIPLES for a sharper row.
    n = int(os.environ.get("BENCH_PIPELINE_TRIPLES", 4_000))
    triples = generate_triples(n, seed=43)
    mesh = make_mesh()
    out = {"n_devices": int(mesh.devices.size), "n_triples": n}
    saved = {k: os.environ.get(k)
             for k in ("RDFIND_PAIR_ROW_BUDGET", "RDFIND_SYNC_PASSES")}
    try:
        # Probe pass: measure this workload's planned per-device pair load at
        # n_pass=1, then pick the row budget that yields n_pass ~ 5 (a blind
        # constant would give 1 pass on small workloads or hundreds on big
        # ones — both useless as an overlap proxy).
        os.environ.pop("RDFIND_PAIR_ROW_BUDGET", None)
        probe: dict = {}
        sharded.discover_sharded(triples, min_support, mesh=mesh, stats=probe)
        caps = probe["planned_caps"]
        full_load = (caps["pairs"] * probe["n_pair_passes"]
                     + caps["giant_pairs"] * probe["n_pair_passes"])
        budget = max(1 << 10, full_load // 5)
        os.environ["RDFIND_PAIR_ROW_BUDGET"] = str(budget)
        out["pair_row_budget"] = budget
        rows, tables = {}, {}
        for mode, sync in (("pipelined", ""), ("sync", "1")):
            os.environ["RDFIND_SYNC_PASSES"] = sync
            stats: dict = {}
            sharded.discover_sharded(triples, min_support, mesh=mesh,
                                     stats=stats)  # warm (compile)
            stats = {}
            t0 = time.perf_counter()
            tables[mode] = sharded.discover_sharded(triples, min_support,
                                                    mesh=mesh, stats=stats)
            rows[mode] = {
                "wall_s": round(time.perf_counter() - t0, 3),
                # Dispatch + fault telemetry via the shared obs key groups
                # (obs/metrics.DISPATCH_KEYS/FAULT_KEYS): bench rows, the
                # driver's --debug lines and the tests render the same
                # names by construction.  The ladder + retry counters prove
                # a degraded run degraded, and a clean one didn't, straight
                # from the artifact.
                **obs_report.dispatch_row(stats),
                # The overlap-efficiency row (dispatch.overlap_report):
                # measured wall vs the serial/parallel bounds — sync mode
                # should meter ~0 efficiency, pipelined mode the real win.
                "overlap": stats.get("overlap"),
                "degradations": stats.get("degradations"),
                "ladder_rung": stats.get("ladder_rung"),
                "cinds": len(tables[mode]),
            }
        out.update(rows)
        out["outputs_identical"] = (tables["pipelined"].to_rows()
                                    == tables["sync"].to_rows())
        out["speedup_vs_sync"] = round(
            rows["sync"]["wall_s"] / max(rows["pipelined"]["wall_s"], 1e-9), 3)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def _bench_exchange(min_support: int) -> dict:
    """Flat vs hierarchical exchange on the sharded pipeline: per-site
    ICI/DCN byte split, wall clock, and the DCN reduction factor the
    per-host combiner bought.  On a single-host run the 2-host pod is
    modeled via RDFIND_HIER_HOSTS (the ledger attributes the flat run's
    cross-host share so the comparison is apples-to-apples); a real
    multi-process run measures the actual topology.  Outputs of the two
    modes are asserted identical in-process — the knob only moves bytes.
    """
    from rdfind_tpu.models import sharded
    from rdfind_tpu.parallel.mesh import make_mesh, topology_hosts
    from rdfind_tpu.utils.synth import generate_triples

    n = int(os.environ.get("BENCH_EXCHANGE_TRIPLES", 4_000))
    triples = generate_triples(n, seed=47)
    mesh = make_mesh()
    num_dev = int(mesh.devices.size)
    out = {"n_devices": num_dev, "n_triples": n}
    saved = {k: os.environ.get(k)
             for k in ("RDFIND_HIER_EXCHANGE", "RDFIND_HIER_HOSTS",
                       "RDFIND_COLLECTIVE_TIMING", "RDFIND_LINK_PROBE")}
    try:
        if topology_hosts(num_dev) == 1 and num_dev % 2 == 0:
            os.environ["RDFIND_HIER_HOSTS"] = "2"  # single-host pod proxy
        hosts = topology_hosts(num_dev)
        out["hosts"] = hosts
        if hosts == 1:
            out["error"] = "device count admits no host factorization"
            return out
        site_cols = ("calls", "capacity", "lanes", "bytes", "ici_bytes",
                     "dcn_bytes", "reply_bytes", "hier", "dcn_capacity",
                     "overflow_retries")
        rows, tables = {}, {}
        for mode, knob in (("flat", "0"), ("hier", "1")):
            os.environ["RDFIND_HIER_EXCHANGE"] = knob
            stats: dict = {}
            sharded.discover_sharded(triples, min_support, mesh=mesh,
                                     use_fis=True, stats=stats)  # warm
            stats = {}
            t0 = time.perf_counter()
            tables[mode] = sharded.discover_sharded(triples, min_support,
                                                    mesh=mesh, use_fis=True,
                                                    stats=stats)
            sites = stats["exchange_sites"]
            rows[mode] = {
                "wall_s": round(time.perf_counter() - t0, 3),
                "ici_bytes": sum(e["ici_bytes"] for e in sites.values()),
                "dcn_bytes": sum(e["dcn_bytes"] for e in sites.values()),
                "bytes": sum(e["bytes"] for e in sites.values()),
                "sites": {s: {k: e[k] for k in site_cols}
                          for s, e in sorted(sites.items())},
                **obs_report.dispatch_row(stats),
                "cinds": len(tables[mode]),
            }
        out.update(rows)
        out["outputs_identical"] = (tables["flat"].to_rows()
                                    == tables["hier"].to_rows())
        out["dcn_reduction"] = round(
            rows["flat"]["dcn_bytes"] / max(rows["hier"]["dcn_bytes"], 1), 3)
        # Per-site collective timing (hier mode, timers + link probe armed):
        # device-synchronized wall per dispatch, achieved GB/s and
        # utilization of the probed per-hop peaks.  The per-hop achieved
        # rates follow from attributing each dispatch's wall to its hops in
        # proportion to their ideal transfer times: achieved_hop =
        # peak_hop * link_util.
        from rdfind_tpu.obs import metrics as obs_metrics

        os.environ["RDFIND_COLLECTIVE_TIMING"] = "1"
        os.environ["RDFIND_LINK_PROBE"] = "1"
        stats = {}
        timed_tbl = sharded.discover_sharded(triples, min_support, mesh=mesh,
                                             use_fis=True, stats=stats)
        caps = obs_metrics.link_caps()
        t_sites = {}
        for s, e in sorted(stats["exchange_sites"].items()):
            if "wall_ms" not in e:
                continue
            row = {k: e[k] for k in ("wall_ms", "gbps", "link_util",
                                     "timed_calls", "timed_bytes", "ideal_ms")
                   if k in e}
            util = e.get("link_util") or 0.0
            if caps.get("ici_gbps"):
                row["ici_gbps"] = round(caps["ici_gbps"] * util, 3)
            if caps.get("dcn_gbps") and e.get("dcn_bytes"):
                row["dcn_gbps"] = round(caps["dcn_gbps"] * util, 3)
            t_sites[s] = row
        out["timing"] = {
            "link_caps": caps,
            "sites": t_sites,
            # The timers are pure measurement: armed vs unarmed must agree.
            "outputs_identical": timed_tbl.to_rows() == tables["hier"].to_rows(),
        }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def _kernel_feed_row(n_mesh: int, min_support: int) -> dict:
    """One kernel-feed row: the sharded dense pass on an n_mesh-device mesh
    with the skew meter armed through the metrics exposition gate (NOT
    RDFIND_COLLECTIVE_TIMING — that serializes the executor and would
    destroy the very overlap this row measures).  Reports pairs/s/chip,
    the executor's measured overlap_efficiency, and the kernel-feed stall
    fraction: exchange-wait ms over dense-compute ms, summed across hosts
    from the _SkewMeter phase timers (>= 1.0 means the dense kernels are
    exchange-bound — feeding the MXU is the bottleneck, not the matmul).
    """
    import tempfile

    from rdfind_tpu.models import sharded
    from rdfind_tpu.obs import metrics as obs_metrics
    from rdfind_tpu.parallel.mesh import make_mesh
    from rdfind_tpu.utils.synth import generate_triples

    n = int(os.environ.get("BENCH_KERNEL_FEED_TRIPLES", 4_000))
    triples = generate_triples(n, seed=53)
    mesh = make_mesh(n_mesh)
    row = {"mesh_devices": int(mesh.devices.size), "n_triples": n}
    prev_export = obs_metrics.export_path()
    tmp = tempfile.NamedTemporaryFile(suffix=".prom", delete=False)
    tmp.close()
    obs_metrics.set_export(tmp.name)
    try:
        stats: dict = {}
        sharded.discover_sharded(triples, min_support, mesh=mesh,
                                 stats=stats)  # warm (compile)
        stats = {}
        t0 = time.perf_counter()
        table = sharded.discover_sharded(triples, min_support, mesh=mesh,
                                         stats=stats)
        wall = time.perf_counter() - t0
    finally:
        obs_metrics.set_export(prev_export)
        try:
            os.unlink(tmp.name)
        except OSError:
            pass
    pairs = int(stats.get("total_pairs", 0))
    ov = stats.get("overlap") or {}
    row.update({
        "wall_s": round(wall, 3),
        "total_pairs": pairs,
        "pairs_per_sec_per_chip": round(
            pairs / max(wall, 1e-9) / max(n_mesh, 1), 1),
        "cinds": len(table),
        "overlap_efficiency": ov.get("overlap_efficiency"),
        "kernel_feed_stall_fraction": obs_report.kernel_feed_stall_fraction(
            stats.get("host_skew")),
        "host_skew": stats.get("host_skew"),
        **obs_report.dispatch_row(stats),
    })
    return row


def _kernel_feed_subprocess(n_mesh: int, timeout_s: int = 1800) -> dict:
    """Run one kernel-feed row in a child process with
    --xla_force_host_platform_device_count (the in-process backend cannot
    grow its device count after init).  The child is bench.py itself in
    BENCH_KERNEL_FEED_WORKER mode; its last stdout line is the row JSON.
    """
    import subprocess

    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={n_mesh}"
    # NB: no --xla_cpu_collective_*timeout* flags here — this image's XLA
    # rejects them at startup (F parse_flags_from_env).  The fake devices
    # share one executable, so collectives are intra-program; the
    # subprocess timeout is the only stuck-guard needed.
    env["XLA_FLAGS"] = flags.strip()
    env["BENCH_BACKEND"] = "cpu"
    env["BENCH_KERNEL_FEED_WORKER"] = str(n_mesh)
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           capture_output=True, text=True, env=env,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"mesh_devices": n_mesh, "proxy": True,
                "error": f"worker timed out after {timeout_s}s"}
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return {"mesh_devices": n_mesh, "proxy": True,
                "error": tail[-1] if tail else f"worker rc={r.returncode}"}
    try:
        row = json.loads(r.stdout.strip().splitlines()[-1])
    except Exception as e:
        return {"mesh_devices": n_mesh, "proxy": True,
                "error": f"unparseable worker output: {type(e).__name__}: {e}"}
    row["proxy"] = True
    return row


def _bench_kernel_feed(min_support: int) -> dict:
    """Multi-chip kernel-feed rows (rung 3): the sharded dense pass at mesh
    sizes 1 and 8, asking whether the exchange plane can keep the dense
    kernels fed as chips are added.  Each row reports pairs/s/chip, the
    executor's overlap_efficiency, and the kernel-feed stall fraction.
    Mesh sizes the in-process backend cannot supply run in a forced-
    device-count CPU subprocess (8 fake devices on one core — per-chip
    absolutes are meaningless there; the row validates the measurement
    STRUCTURE, and scaling_efficiency is only computed when both rows ran
    on real same-process devices).  Pod-slice rows are reserved for
    tpu_watch captures on the real machine.
    """
    import jax

    avail = int(jax.device_count())
    out = {"n_devices_available": avail, "rows": []}
    for n_mesh in (1, 8):
        if n_mesh <= avail:
            try:
                row = _kernel_feed_row(n_mesh, min_support)
                row["proxy"] = False
            except Exception as e:
                row = {"mesh_devices": n_mesh,
                       "error": f"{type(e).__name__}: {e}"}
        else:
            row = _kernel_feed_subprocess(n_mesh)
        out["rows"].append(row)
        # Dict view for the sentinel (its _dig walks dicts only).
        out[f"mesh{n_mesh}"] = row
    real = [r for r in out["rows"]
            if not r.get("proxy") and r.get("pairs_per_sec_per_chip")]
    if len(real) >= 2:
        out["scaling_efficiency"] = round(
            real[-1]["pairs_per_sec_per_chip"]
            / real[0]["pairs_per_sec_per_chip"], 3)
    return out


def _bench_ingest() -> dict:
    """Native ingest rows on a generated multi-file workload.

    Builds BENCH_INGEST_FILES plain N-Triples files (one of them gz so the
    gz path is exercised too) and measures four rows, all asserted
    bit-identical:

    * ``serial_legacy`` — 1 thread, every speed rung OFF (scalar scan,
      fread + arena copies, no gz pipeline): the pre-SWAR engine, kept as
      the denominator for ``parse_speedup_vs_legacy``;
    * ``serial`` — 1 thread, rungs at their env-resolved defaults (SWAR +
      mmap zero-copy): the single-thread acceptance row;
    * ``parallel`` — BENCH_INGEST_THREADS (default: auto = physical cores
      clamped to affinity) workers;
    * ``parallel_forced`` — only when auto resolves to 1 (1-core box): 2
      workers, so the parallel engine is still exercised and its
      determinism recorded, clearly labeled as oversubscribed.

    `n_cores` is recorded so a 1-core proxy row cannot be mistaken for a
    parallel-speedup measurement (the parallel acceptance bar needs
    >= 4 cores).
    """
    import gzip
    import tempfile

    from rdfind_tpu.io import native as native_io

    if not native_io.available():
        return {"error": "native ingest unavailable"}
    n_lines = int(os.environ.get("BENCH_INGEST_LINES", 400_000))
    n_files = int(os.environ.get("BENCH_INGEST_FILES", 8))
    threads = int(os.environ.get("BENCH_INGEST_THREADS") or
                  native_io.ingest_threads())
    rng = np.random.default_rng(11)
    out = {"n_cores": os.cpu_count(),
           "n_physical_cores": native_io.physical_cores(),
           "threads": threads, "n_files": n_files, "n_lines": n_lines}
    with tempfile.TemporaryDirectory() as td:
        paths = []
        per_file = max(n_lines // n_files, 1)
        for fi in range(n_files):
            s = rng.integers(0, 60_000, per_file)
            p = rng.integers(0, 240, per_file)
            o = rng.integers(0, 25_000, per_file)
            lines = "".join(
                f"<http://ex/s{a}> <http://ex/p{b}> \"lit {c}\" .\n"
                for a, b, c in zip(s, p, o))
            if fi == n_files - 1:  # one gz member: file-level parallelism only
                path = os.path.join(td, f"f{fi}.nt.gz")
                with gzip.open(path, "wt") as g:
                    g.write(lines)
            else:
                path = os.path.join(td, f"f{fi}.nt")
                with open(path, "w") as f:
                    f.write(lines)
            paths.append(path)
        out["input_bytes"] = sum(os.path.getsize(p) for p in paths)
        legacy_env = {"RDFIND_INGEST_SWAR": "0", "RDFIND_INGEST_MMAP": "0",
                      "RDFIND_INGEST_GZ_PIPELINE": "0"}
        saved = {k: os.environ.get(k) for k in legacy_env}
        os.environ.update(legacy_env)
        try:
            st: dict = {}
            ids_l, d_l = native_io.ingest_files(paths, threads=1, stats=st)
            out["serial_legacy"] = st
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        modes = [("serial", 1), ("parallel", threads)]
        if threads <= 1:
            modes.append(("parallel_forced", 2))
        results = {}
        for mode, t in modes:
            st = {}
            ids, d = native_io.ingest_files(paths, threads=t, stats=st)
            results[mode] = (ids, d)
            out[mode] = st
        ids_s, d_s = results["serial"]
        out["outputs_identical"] = bool(
            np.array_equal(ids_s, ids_l)
            and list(d_s.values) == list(d_l.values)
            and all(np.array_equal(ids_s, ids_m)
                    and list(d_s.values) == list(d_m.values)
                    for mode, (ids_m, d_m) in results.items()
                    if mode != "serial"))
        out["speedup_vs_serial"] = round(
            out["parallel"]["triples_per_sec"]
            / max(out["serial"]["triples_per_sec"], 1e-9), 3)
        out["parse_speedup_vs_legacy"] = round(
            out["serial_legacy"]["parse_ms"]
            / max(out["serial"]["parse_ms"], 1e-9), 3)
    return out


def _run(n: int, min_support: int) -> dict:
    backend = _init_backend()

    from rdfind_tpu import oracle
    from rdfind_tpu.models import allatonce
    from rdfind_tpu.utils.synth import generate_triples

    triples = generate_triples(n, seed=42)

    # Warm-up (compile cache) on the same shapes, then measure.
    stats = {}
    allatonce.discover(triples, min_support, stats=stats)
    t0 = time.perf_counter()
    table = allatonce.discover(triples, min_support, stats=stats)
    elapsed = time.perf_counter() - t0
    pairs_per_sec = stats["total_pairs"] / elapsed

    # Oracle baseline: the single-core pure-Python joinline oracle on the SAME
    # workload (like-for-like; the r2 subsample extrapolation understated the
    # oracle's superlinear pair load).  ~15 s at the 200k default.
    all_t = [tuple(int(x) for x in row) for row in triples]
    t0 = time.perf_counter()
    oracle.discover_cinds_joinline(all_t, min_support)
    oracle_elapsed = time.perf_counter() - t0
    oracle_pairs_per_sec = stats["total_pairs"] / oracle_elapsed

    fallback_extra = {}
    if FALLBACK_REASON is not None:
        fallback_extra["backend_note"] = (
            FALLBACK_REASON + "; CPU fallback — see BASELINE.md for the "
            "measured real-chip headline")
        # Embed the same-round on-chip artifact (captured by bench.py/
        # tpu_watch.py while the tunnel answered) so the record of a
        # CPU-fallback run still carries the measured TPU numbers inline.
        artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_TPU_HEADLINE.json")
        try:
            with open(artifact) as f:
                captured = json.load(f)
            if (isinstance(captured, dict)
                    and isinstance(captured.get("detail"), dict)
                    and captured["detail"].get("backend") == "tpu"):
                fallback_extra["tpu_headline_artifact"] = captured
        except (OSError, ValueError):
            pass

    detail = {
        "backend": backend,
        **fallback_extra,
        # Row identity for the regression sentinel: git sha, core count and
        # the resolved RDFIND_* knob set this run measured under.
        "provenance": obs_sentinel.provenance(backend=backend),
        "n_triples": n, "min_support": min_support,
        "wall_s": round(elapsed, 3), "total_pairs": stats["total_pairs"],
        "n_lines": stats["n_lines"], "max_line": stats["max_line"],
        "cinds": len(table),
        "pair_backend": stats.get("pair_backend"),
        # Occupancy-corrected roofline inputs: the resolved membership dtype
        # and the dense plan's real/issued-FLOP record for THIS workload.
        "cooc_dtype": stats.get("cooc_dtype"),
        "dense_plan": stats.get("dense_plan"),
        # Degradation ledger of the headline run (None on a fault-free run).
        "degradations": stats.get("degradations"),
        "oracle_wall_s": round(oracle_elapsed, 3),
        "oracle_pairs_per_sec": round(oracle_pairs_per_sec, 1),
        # Integrity plane: the headline run's output digest plus the
        # workload it was computed over.  The sentinel compares digests only
        # between rows with the same workload (and provenance key), so a
        # digest change there is a correctness — not perf — regression.
        "output_digest": obs_integrity.digest_hex(
            *obs_integrity.digest_table(table)),
        "workload": {"n_triples": n, "min_support": min_support,
                     "seed": 42},
    }

    # The DEFAULT strategy (SmallToLarge, id 1) on the same workload, so the
    # default path always has a recorded number too (best-effort).
    try:
        from rdfind_tpu.models import small_to_large
        s2l_stats: dict = {}
        small_to_large.discover(triples, min_support, stats=s2l_stats)  # warm
        s2l_stats.clear()
        t0 = time.perf_counter()
        s2l_table = small_to_large.discover(triples, min_support,
                                            stats=s2l_stats)
        s2l_wall = time.perf_counter() - t0
        detail["s2l"] = {
            "wall_s": round(s2l_wall, 3),
            "total_pairs": int(s2l_stats.get("total_pairs", 0)),
            "pairs_per_sec": round(
                s2l_stats.get("total_pairs", 0) / s2l_wall, 1),
            "cinds": len(s2l_table),
        }
    except Exception as e:
        detail["s2l"] = {"error": f"{type(e).__name__}: {e}"}

    # Strategy 2 on the same workload: the sketch round + dense-matmul
    # verification (r4 rework; was the chunked host loop, the strategy's
    # TPU-matrix laggard at 32.5 s on this workload's config-1 sibling).
    try:
        from rdfind_tpu.models import approximate
        ap_stats: dict = {}
        approximate.discover(triples, min_support, stats=ap_stats)  # warm
        ap_stats.clear()
        t0 = time.perf_counter()
        ap_table = approximate.discover(triples, min_support, stats=ap_stats)
        ap_wall = time.perf_counter() - t0
        detail["approx"] = {
            "wall_s": round(ap_wall, 3),
            "total_pairs": int(ap_stats.get("total_pairs", 0)),
            "pairs_per_sec": round(
                ap_stats.get("total_pairs", 0) / ap_wall, 1),
            "pair_backend": ap_stats.get("pair_backend"),
            "cinds": len(ap_table),
        }
    except Exception as e:
        detail["approx"] = {"error": f"{type(e).__name__}: {e}"}

    # Roofline: achieved FLOP/s of the dense cooc matmul vs chip peak
    # (VERDICT r3: pairs/s alone cannot show how much headroom remains).
    try:
        detail["mfu"] = _measure_mfu(stats, backend)
    except Exception as e:
        detail["mfu"] = {"error": f"{type(e).__name__}: {e}"}

    # Pipelined pass executor vs forced-sync on a multi-pass streaming
    # workload (dispatch-overlap telemetry; CPU proxy until the tunnel is
    # back, real overlap numbers on TPU).
    try:
        detail["pipelined_passes"] = _bench_pipelined_passes(min_support)
    except Exception as e:
        detail["pipelined_passes"] = {"error": f"{type(e).__name__}: {e}"}

    # Flat vs hierarchical exchange on the 2-host pod proxy (per-site
    # ICI/DCN split + the combiner's DCN reduction; a multi-process run
    # measures the real topology instead).
    try:
        detail["exchange"] = _bench_exchange(min_support)
    except Exception as e:
        detail["exchange"] = {"error": f"{type(e).__name__}: {e}"}

    # Multi-chip kernel-feed rows (rung 3): the sharded dense pass at mesh
    # 1 and 8 — pairs/s/chip, overlap efficiency, and how long the dense
    # kernels starved on exchange (stall fraction).  Mesh sizes beyond the
    # local device count run on the forced-device-count CPU subprocess.
    try:
        detail["kernel_feed"] = _bench_kernel_feed(min_support)
    except Exception as e:
        detail["kernel_feed"] = {"error": f"{type(e).__name__}: {e}"}

    # Parallel native ingest vs the serial engine (front-door throughput:
    # triples/s, bytes/s, per-phase ms, identical-output check).
    try:
        detail["ingest"] = _bench_ingest()
    except Exception as e:
        detail["ingest"] = {"error": f"{type(e).__name__}: {e}"}

    # Data-plane snapshot of the headline workload: the log2 join-line and
    # capture-support distributions (obs/datastats.py), which bench rows
    # never recorded before — one extra discover with RDFIND_DATASTATS
    # forced on, so the measured walls above stay on the disabled path.
    try:
        ds_stats: dict = {}
        prev_ds = os.environ.get("RDFIND_DATASTATS")
        os.environ["RDFIND_DATASTATS"] = "1"
        try:
            allatonce.discover(triples, min_support, stats=ds_stats)
        finally:
            if prev_ds is None:
                os.environ.pop("RDFIND_DATASTATS", None)
            else:
                os.environ["RDFIND_DATASTATS"] = prev_ds
        detail["datastats"] = {
            k: ds_stats[k] for k in ("datastats_lines", "datastats_captures",
                                     "datastats_block_skip") if k in ds_stats}
    except Exception as e:
        detail["datastats"] = {"error": f"{type(e).__name__}: {e}"}

    # Unified obs snapshot (ISSUE 5): the metrics-registry mirror of every
    # stats key the process published (dispatch + exchange + ingest + fault
    # telemetry, accumulated across the rows above) plus the current device
    # memory watermarks — ONE schema for every BENCH_* artifact going
    # forward.
    try:
        detail["obs"] = obs.snapshot()
    except Exception as e:
        detail["obs"] = {"error": f"{type(e).__name__}: {e}"}

    # Collective-watchdog cost row: the disabled guard is on every dispatch
    # of every run, so its per-hit cost is a standing tax — sample it here
    # (micro-loop over the real guard path) next to the counters the
    # headline run accumulated, so the <2% overhead budget asserted in
    # tests/test_watchdog.py stays visible in every BENCH_* artifact.
    try:
        from rdfind_tpu.runtime import watchdog
        reps = 20000
        t0 = time.perf_counter()
        for _ in range(reps):
            with watchdog.collective("pairs", nbytes=1024):
                pass
        per_guard_us = (time.perf_counter() - t0) / reps * 1e6
        detail["watchdog"] = {
            "disabled_per_guard_us": round(per_guard_us, 3),
            **watchdog.snapshot(),
        }
    except Exception as e:
        detail["watchdog"] = {"error": f"{type(e).__name__}: {e}"}

    # Pallas packed-bitset kernel vs jnp planes path, on this backend.
    try:
        from rdfind_tpu.ops import sketch
        pk = sketch.kernel_selfcheck(n_rows=1024, n_bits=4096,
                                     backend=backend)
        if backend == "tpu" and "pallas_ms" in pk:
            # Fraction-of-peak for the containment kernel: the same logical
            # contraction as a dense bf16 matmul is 2*D*R*bits FLOPs, so
            # effective FLOP/s = that work over the packed kernel's time.
            gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
            eq_flops = 2.0 * pk["n_rows"] * pk["n_rows"] * pk["bits"]
            eff = eq_flops / (pk["pallas_ms"] / 1e3)
            pk["equiv_dense_tflops"] = round(eff / 1e12, 3)
            if gen in TPU_PEAKS:
                pk["peak_fraction"] = round(
                    eff / (TPU_PEAKS[gen]["bf16_tflops"] * 1e12), 4)
                pk["hbm_peak_fraction"] = round(
                    pk["pallas_gbps"] / TPU_PEAKS[gen]["hbm_gbps"], 4)
            # Roofline row at a launch-amortized shape: the 1024-row probe is
            # dispatch-bound; 8192 rows move ~600 MB/call, enough to read the
            # kernel's real HBM bandwidth (VERDICT r4 item 7).
            try:
                big = sketch.kernel_selfcheck(n_rows=8192, n_bits=4096,
                                              backend=backend, repeats=3)
                pk["roofline_8k"] = {
                    k: big[k] for k in ("pallas_ms", "pallas_kernel_ms",
                                        "jnp_ms", "speedup",
                                        "hbm_bytes_model", "pallas_gbps")
                    if k in big}
                if gen in TPU_PEAKS and "pallas_gbps" in big:
                    pk["roofline_8k"]["hbm_peak_fraction"] = round(
                        big["pallas_gbps"] / TPU_PEAKS[gen]["hbm_gbps"], 4)
            except Exception as e:
                pk["roofline_8k"] = {"error": f"{type(e).__name__}: {e}"}
        # Per-mode rows: plane bits x fused verdict, each with an HBM
        # watermark sample (rung-2 acceptance: the fused row's peak must
        # undercut the materialized row's by the cooc tile it never writes).
        try:
            km = _bench_kernel_modes(backend)
            pk["modes"] = km["modes"]
            pk["modes_by_name"] = km["by_mode"]
        except Exception as e:
            pk["modes"] = {"error": f"{type(e).__name__}: {e}"}
        detail["pallas_vs_jnp"] = pk
    except Exception as e:  # kernel comparison is best-effort
        detail["pallas_vs_jnp"] = {"error": f"{type(e).__name__}: {e}"}

    return {
        "metric": "cind_pairs_checked_per_sec_per_chip",
        "value": round(pairs_per_sec, 1),
        "unit": "pairs/s",
        "vs_baseline": round(pairs_per_sec / oracle_pairs_per_sec, 3),
        "detail": detail,
    }


def _record_history(result: dict) -> None:
    """Append this run to the sentinel's BENCH_HISTORY.jsonl (stderr-only
    reporting: stdout stays the single JSON result line).  BENCH_HISTORY
    overrides the path; "0" disables."""
    dest = os.environ.get("BENCH_HISTORY", "")
    if dest == "0":
        return
    try:
        row = obs_sentinel.append(result, path=dest or None)
        print(f"bench: history row appended (sha={row['sha']}, "
              f"{len(row['metrics'])} metrics)", file=sys.stderr, flush=True)
    except Exception as e:  # history is telemetry, never a bench failure
        print(f"bench: history append failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)


def main():
    n = int(os.environ.get("BENCH_TRIPLES", 200_000))
    min_support = int(os.environ.get("BENCH_MIN_SUPPORT", 10))
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if os.environ.get("BENCH_KERNEL_FEED_WORKER"):
        # Child of _kernel_feed_subprocess: one kernel-feed row on the
        # forced-device-count backend, row JSON on stdout, no history.
        n_mesh = int(os.environ["BENCH_KERNEL_FEED_WORKER"])
        try:
            _init_backend()
            row = _kernel_feed_row(n_mesh, min_support)
        except Exception as e:
            row = {"mesh_devices": n_mesh,
                   "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(row))
        return
    if os.environ.get("BENCH_KERNEL_MODES_ONLY"):
        # Fast standalone artifact for the rung-3 kernel-mode grid (plane
        # bits x emit_pipeline x fused): no oracle, no headline discovery —
        # the cheap rows tpu_watch captures FIRST on a freshly live tunnel,
        # before risking the long benches.  Same detail shape bench.py
        # embeds under detail.pallas_vs_jnp, promoted to the headline.
        try:
            backend = _init_backend()
            km = _bench_kernel_modes(backend)
            walls = [r["pallas_ms"] for r in km["modes"]
                     if isinstance(r.get("pallas_ms"), (int, float))]
            result = {
                "metric": "kernel_mode_best_pallas_ms",
                "value": min(walls) if walls else 0,
                "unit": "ms", "vs_baseline": 1.0,
                "detail": {"backend": backend,
                           "pallas_vs_jnp": {"modes": km["modes"],
                                             "modes_by_name": km["by_mode"]},
                           "obs": obs.snapshot()},
            }
        except Exception as e:
            result = {"metric": "kernel_mode_best_pallas_ms", "value": 0,
                      "unit": "ms", "vs_baseline": 0,
                      "detail": {"error": f"{type(e).__name__}: {e}"}}
        print(json.dumps(result))
        _record_history(result)
        return
    if os.environ.get("BENCH_INGEST_ONLY"):
        # Fast standalone artifact for the ingest row (no jax warm-up, no
        # discovery): the same JSON shape bench.py embeds under
        # detail.ingest, promoted to the headline.
        try:
            ing = _bench_ingest()
            value = ing.get("parallel", {}).get("triples_per_sec", 0)
            base = ing.get("serial", {}).get("triples_per_sec", 0)
            result = {
                "metric": "ingest_triples_per_sec",
                "value": value, "unit": "triples/s",
                "vs_baseline": round(value / max(base, 1e-9), 3),
                "detail": {"ingest": ing, "obs": obs.snapshot()},
            }
        except Exception as e:
            result = {"metric": "ingest_triples_per_sec", "value": 0,
                      "unit": "triples/s", "vs_baseline": 0,
                      "detail": {"error": f"{type(e).__name__}: {e}"}}
        print(json.dumps(result))
        _record_history(result)
        return
    try:
        result = _run(n, min_support)
    except Exception as e:
        tb = traceback.format_exc(limit=3)
        result = {
            "metric": "cind_pairs_checked_per_sec_per_chip",
            "value": 0,
            "unit": "pairs/s",
            "vs_baseline": 0,
            "detail": {"error": f"{type(e).__name__}: {e}",
                       "traceback": tb.splitlines()[-3:]},
        }
    print(json.dumps(result))
    _record_history(result)


if __name__ == "__main__":
    main()
