"""TPU-recovery watcher: probe the axon tunnel until it answers, then bench.

This is the recovery artifact BASELINE.md promises (round-3 advice finding:
the doc claimed "a background watcher retries the tunnel" but no watcher was
committed).  The axon tunnel admits one client at a time and can wedge
indefinitely after a holder is killed; probing in a killable subprocess is
the only reliable verdict (see bench.py:_probe_tpu_subprocess).

Loop: probe -> on success run `bench.py` in kernel-modes-only mode (the
fast rung-3 plane-bits x emit_pipeline grid -> BENCH_TPU_KERNEL_MODES.json
+ provenance-keyed BENCH_HISTORY.jsonl rows, captured FIRST so a re-wedge
mid-headline loses nothing), then `bench.py` (headline) and
`bench_matrix.py` (configs 1-2 x strategies 0/1/2/3), append rows to
BENCH_TPU_MATRIX.jsonl, write the headline line to
BENCH_TPU_HEADLINE.json, then exit.  On failure
sleep and retry until --deadline-h expires or a `tpu_watch.stop` file
appears next to this script.

Run detached:  nohup python tpu_watch.py >> tpu_watch.log 2>&1 &

Mirrors the reference's always-reporting measurement discipline
(AbstractFlinkProgram.java:65-77,175-182): every probe attempt and every
outcome is logged; the watcher never exits silently.

Liveness (obs integration): the watcher writes its own heartbeat/status
file (phase, attempt, last-event timestamp) into its obs directory via
rdfind_tpu.obs.heartbeat, so "is the watcher wedged inside a bench or just
sleeping between probes" is answerable without reading the log.  The same
machinery reads any RUN's obs directory back: ``tpu_watch.py --status DIR``
prints alive/wedged/done (+ the stage/pass the run is inside) and exits
0/1/2 — the wedged-vs-slow verdict for traced rdfind runs (--trace DIR) —
or 3 (CORRUPT) when a host's heartbeat carries an unrepaired integrity
digest mismatch (the run may be moving, but its output is not attested).
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
STOP_FILE = os.path.join(REPO, "tpu_watch.stop")
OBS_DIR = os.path.join(REPO, "tpu_watch_obs")

sys.path.insert(0, REPO)
from rdfind_tpu.obs import flightrec, heartbeat  # noqa: E402 (after sys.path fix)

_STATUS = {"phase": "starting", "attempt": 0}


def beat(**status) -> None:
    """Update + persist the watcher's own heartbeat (never fails the loop)."""
    _STATUS.update(status)
    try:
        heartbeat.write(OBS_DIR, dict(_STATUS, stage=_STATUS["phase"]))
    except Exception:
        pass


def log(msg: str) -> None:
    print(f"[tpu_watch {time.strftime('%H:%M:%S')}] {msg}", flush=True)
    beat(last_message=msg)


def probe(timeout_s: int = 120) -> bool:
    """Probe the default (axon/TPU) backend in a killable subprocess."""
    code = ("import jax, jax.numpy as jnp;"
            "d = jax.devices();"
            "jax.block_until_ready(jnp.zeros((8,), jnp.int32) + 1);"
            "print(d[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s, cwd=REPO)
    except subprocess.TimeoutExpired:
        log(f"probe timed out after {timeout_s}s")
        return False
    if r.returncode == 0 and r.stdout.strip().splitlines()[-1:] != ["cpu"]:
        log(f"probe ok: platform={r.stdout.strip().splitlines()[-1]}")
        return True
    tail = (r.stderr or "").strip().splitlines()[-1:] or [f"rc={r.returncode}"]
    log(f"probe failed: {tail[0]}")
    return False


def is_tpu_bench_line(line: str) -> bool:
    """True iff a bench.py output line is a REAL on-chip measurement.

    Structured check, not a substring: a CPU-fallback line EMBEDS the
    previous TPU artifact (which contains '"backend": "tpu"' inside
    detail.tpu_headline_artifact), and must not overwrite it."""
    try:
        parsed = json.loads(line)
    except json.JSONDecodeError:
        return False
    return (isinstance(parsed, dict)
            and isinstance(parsed.get("detail"), dict)
            and parsed["detail"].get("backend") == "tpu")


def run_benches() -> bool:
    """Run the headline bench + the config matrix on the (live) TPU.

    Generous timeouts: killing a TPU-holding process mid-run is what wedges
    the tunnel in the first place, so these only fire as a last resort.
    """
    ok = True
    env = dict(os.environ)
    # Rung-3 kernel-mode grid first (planes {8,4,2} x emit on/off x fused):
    # minutes, not the headline's half hour — so a tunnel that wedges again
    # mid-headline still leaves the kernel rows in BENCH_HISTORY.jsonl
    # (bench.py appends them provenance-keyed itself; the artifact file
    # here is the human-readable mirror).
    log("running bench.py (rung-3 kernel modes)...")
    try:
        r = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                           text=True, timeout=1800, cwd=REPO,
                           env=dict(env, BENCH_KERNEL_MODES_ONLY="1"))
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        log(f"bench.py kernel modes rc={r.returncode}: {line[:200]}")
        if r.returncode == 0 and is_tpu_bench_line(line):
            with open(os.path.join(REPO,
                                   "BENCH_TPU_KERNEL_MODES.json"), "w") as f:
                f.write(line + "\n")
        else:
            ok = False
    except subprocess.TimeoutExpired:
        log("bench.py kernel modes timed out (1800s)")
        ok = False

    log("running bench.py (headline)...")
    try:
        r = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                           text=True, timeout=2400, cwd=REPO, env=env)
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        log(f"bench.py rc={r.returncode}: {line[:200]}")
        on_tpu = r.returncode == 0 and is_tpu_bench_line(line)
        if on_tpu:
            # Only a real-TPU row may become the headline artifact (a CPU
            # fallback exiting rc=0 must not masquerade as the TPU number).
            with open(os.path.join(REPO, "BENCH_TPU_HEADLINE.json"), "w") as f:
                f.write(line + "\n")
        ok &= on_tpu
    except subprocess.TimeoutExpired:
        log("bench.py timed out (2400s)")
        ok = False

    log("running bench_matrix.py (configs 1-2 x strategies 0,1,2,3)...")
    try:
        r = subprocess.run([sys.executable, "bench_matrix.py"],
                           capture_output=True, text=True, timeout=5400,
                           cwd=REPO, env=env)
        rows = []
        for ln in r.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                log(f"  matrix: unparseable row {ln[:120]!r}")
        log(f"bench_matrix.py rc={r.returncode}: {len(rows)} rows")
        for ln in (r.stderr or "").strip().splitlines():
            log(f"  matrix: {ln}")
        tpu_rows = [row for row in rows if row.get("backend") == "tpu"]
        if tpu_rows:
            with open(os.path.join(REPO, "BENCH_TPU_MATRIX.jsonl"), "a") as f:
                stamp = time.strftime("%Y-%m-%dT%H:%M:%S")
                for row in tpu_rows:
                    row["captured_at"] = stamp
                    f.write(json.dumps(row) + "\n")
        ok &= r.returncode == 0 and bool(tpu_rows)
    except subprocess.TimeoutExpired:
        log("bench_matrix.py timed out (5400s)")
        ok = False
    return ok


def _flightrec_summaries(obs_dir: str) -> dict:
    """Per-host flight-recorder dump summaries found next to the heartbeats
    (path + reason + event count + the last few event names) — the
    post-mortem pointer a wedge verdict should hand the operator."""
    out = {}
    for host, path in sorted(flightrec.find_dumps(obs_dir).items()):
        try:
            d = flightrec.load(path)
            events = d.get("events", [])
            out[host] = {
                "path": path,
                "reason": d.get("reason"),
                "dumped_at": d.get("dumped_at"),
                "n_events": d.get("n_events", len(events)),
                "last_events": [e.get("name") for e in events[-5:]],
            }
        except Exception as e:
            out[host] = {"path": path,
                         "error": f"{type(e).__name__}: {e}"}
    return out


def _degrading_hosts(hosts: dict) -> dict:
    """{host: forecast-advisory} for hosts whose heartbeat carries a
    cap-exhaustion forecast.  "Degrading" is a distinct verdict from
    "wedged": spans are still closing (the run is alive), but a cap is
    forecast to exhaust before the planned pass count — the degradation
    ladder (grow/split/skip) is about to fire, not the tunnel."""
    return {h: b["forecast"] for h, b in hosts.items()
            if isinstance(b.get("forecast"), dict)}


def _recovering_hosts(hosts: dict) -> dict:
    """{host: watchdog-verdict} for hosts whose heartbeat status says the
    collective watchdog fired and the run is re-entering via elastic resume
    (runtime/watchdog.py stamps ``recovering=True`` + ``watchdog=wedged@site``
    on fire; the supervisor clears the flag once a re-entered attempt
    completes).  RECOVERING is a distinct verdict from "wedged": the stall
    was already detected and converted to a preemption — the run is expected
    to come back on its own, so the exit code stays 0."""
    return {h: (b.get("watchdog") or "wedged@?") for h, b in hosts.items()
            if b.get("recovering") and not b.get("final")}


def _corrupt_hosts(hosts: dict) -> dict:
    """{host: integrity-verdict} for hosts whose heartbeat carries an
    unrepaired integrity-digest mismatch (obs/integrity.note_mismatch pushes
    it onto the run status).  CORRUPT is distinct from both "wedged" (the
    run may still be moving) and "degrading" (a cap forecast): the output
    of this run can no longer be trusted bit-for-bit."""
    return {h: b["integrity"] for h, b in hosts.items()
            if isinstance(b.get("integrity"), dict)
            and b["integrity"].get("corrupt")}


def _serving_stale_hosts(hosts: dict) -> dict:
    """Hosts running a serving process whose bundle dir holds a NEWER
    committed index generation than the one loaded (a pending or refused
    hot swap — the answers are correct but out of date).  A serve host is
    never "wedged" (idle is its steady state); staleness is its verdict."""
    out = {}
    for h, b in hosts.items():
        if b.get("mode") != "serve" or b.get("final"):
            continue
        gen, bgen = b.get("generation"), b.get("bundle_generation")
        behind = bgen is not None and (gen is None or bgen > gen)
        if b.get("index_stale") or behind:
            out[h] = {"generation": gen, "bundle_generation": bgen,
                      "pending": b.get("pending_swap")}
    return out


def _slo_burning_hosts(hosts: dict) -> dict:
    """Hosts running a serving process whose SLO engine reports a
    sustained burn (both burn-rate windows over target — obs/servestats).
    SLO-BURNING is distinct from SERVING-STALE: staleness says the data
    is out of date; a burn says the service itself (latency, errors, or
    freshness) is violating its target right now."""
    out = {}
    for h, b in hosts.items():
        if b.get("mode") != "serve" or b.get("final"):
            continue
        slo = b.get("slo")
        if isinstance(slo, dict) and slo.get("state") == "burning":
            out[h] = slo
    return out


def report_status(obs_dir: str, stale_s: float, as_json: bool = False) -> int:
    """The wedged-vs-slow verdict over a run's obs directory (exit codes:
    0 alive/done, 1 wedged, 2 no heartbeat at all, 3 CORRUPT — an
    unrepaired integrity mismatch on some host's heartbeat; "degrading" and
    "recovering" are reported but never change the exit code — the run is
    still making progress, or is expected to come back via elastic
    resume)."""
    verdict = heartbeat.assess(obs_dir, stale_s=stale_s)
    state = verdict["state"]
    hosts = {
        h: {**b, "stale": b["age_s"] > stale_s and not b.get("final")}
        for h, b in verdict["hosts"].items()}
    degrading = _degrading_hosts(hosts)
    recovering = _recovering_hosts(hosts)
    corrupt = _corrupt_hosts(hosts)
    serving_stale = _serving_stale_hosts(hosts)
    slo_burning = _slo_burning_hosts(hosts)
    recs = _flightrec_summaries(obs_dir)
    if as_json:
        print(json.dumps({"dir": obs_dir, "state": state,
                          "degrading": bool(degrading),
                          "recovering": bool(recovering),
                          "corrupt": bool(corrupt),
                          "serving_stale": bool(serving_stale),
                          "slo_burning": bool(slo_burning),
                          "stale_s": stale_s, "age_s": verdict["age_s"],
                          "hosts": hosts, "flightrec": recs},
                         sort_keys=True, default=str))
        if state == "missing":
            return 2
        if corrupt:
            return 3
        return 1 if state == "wedged" else 0
    if state == "missing":
        print(f"status[{obs_dir}]: no heartbeat files "
              f"(not a traced run directory, or the run never started)")
        return 2
    for h, b in sorted(hosts.items()):
        where = b.get("stage")
        if b.get("pass") is not None:
            where = f"{where} pass {b.get('pass')}"
        # Run mode rides the heartbeat status (runtime/delta.py sets
        # mode=delta + the base generation; the driver sets mode=full), so
        # an operator can tell an incremental replay from a full rebuild
        # without reading the run's stats.
        if b.get("mode") == "delta":
            where = f"{where} [delta, base gen {b.get('generation')}]"
        elif b.get("mode") == "serve":
            where = f"{where} [serve, gen {b.get('generation')}]"
        elif b.get("mode"):
            where = f"{where} [{b.get('mode')}]"
        flags = (" (final)" if b.get("final") else
                 " (STALE)" if b["stale"] else "")
        print(f"status[{obs_dir}] host {h}: last event {b['age_s']}s ago "
              f"in {where}" + flags)
        util = b.get("cap_util")
        if isinstance(util, dict):
            caps = ", ".join(f"{k}={v}" for k, v in sorted(util.items())
                             if k != "pass")
            print(f"status[{obs_dir}] host {h}: cap utilization "
                  f"(pass {util.get('pass')}): {caps}")
        wd = recovering.get(h)
        if wd is not None:
            print(f"status[{obs_dir}] host {h}: RECOVERING — collective "
                  f"watchdog fired ({wd}); converted to a preemption, "
                  f"elastic resume re-entering")
        fc = degrading.get(h)
        if fc is not None:
            print(f"status[{obs_dir}] host {h}: DEGRADING — cap "
                  f"{fc.get('cap')} forecast exhausted at pass "
                  f"{fc.get('predicted_pass')} ({fc.get('reason')}, frac "
                  f"{fc.get('frac')})")
        iv = corrupt.get(h)
        if iv is not None:
            print(f"status[{obs_dir}] host {h}: CORRUPT — integrity digest "
                  f"mismatch at {iv.get('site')} ({iv.get('stage')}); the "
                  f"output is not digest-attested")
        sv = serving_stale.get(h)
        if sv is not None:
            why = (f"; last swap refused: {sv['pending']}"
                   if sv.get("pending") else "")
            print(f"status[{obs_dir}] host {h}: SERVING-STALE — bundle "
                  f"dir committed generation {sv['bundle_generation']} "
                  f"but the server still answers from "
                  f"{sv['generation']}{why}")
        if b.get("mode") == "serve" and not b.get("final") \
                and "index_age_s" in b:
            print(f"status[{obs_dir}] host {h}: freshness — index age "
                  f"{b.get('index_age_s')}s, staleness "
                  f"{b.get('staleness_s')}s, "
                  f"{b.get('generations_behind')} generation(s) behind")
        burn = slo_burning.get(h)
        if burn is not None:
            print(f"status[{obs_dir}] host {h}: SLO-BURNING — "
                  f"{burn.get('slo')} SLO over target on both burn-rate "
                  f"windows (the service is violating its target now, "
                  f"not momentarily)")
    # Surface the wedged host's flight recorder when one was dumped: the
    # ring of events leading into the stall, captured even with the jsonl
    # tracer off.
    for h, r in sorted(recs.items()):
        if "error" in r:
            print(f"status[{obs_dir}] host {h}: flight recorder at "
                  f"{r['path']} unreadable ({r['error']})")
            continue
        print(f"status[{obs_dir}] host {h}: flight recorder "
              f"({r['n_events']} events, reason={r['reason']!r}) at "
              f"{r['path']}; last: {', '.join(map(str, r['last_events']))}")
    tail = ""
    if corrupt:
        tail = (f" (CORRUPT: unrepaired integrity mismatch on host(s) "
                f"{sorted(corrupt)})")
    elif state == "wedged":
        tail = f" (no span boundary for > {stale_s:.0f}s — wedged, not slow)"
    elif recovering:
        tail = (" (RECOVERING: collective watchdog fired on host(s) "
                f"{sorted(recovering)} — wedge already converted to a "
                "preemption, elastic resume in flight)")
    elif degrading:
        tail = (" (degrading: cap-exhaustion forecast active on host(s) "
                f"{sorted(degrading)} — alive, but the degradation ladder "
                "is imminent)")
    elif slo_burning:
        names = sorted({v.get("slo") for v in slo_burning.values()})
        tail = (f" (SLO-BURNING: host(s) {sorted(slo_burning)} over "
                f"target on {', '.join(map(str, names))} — sustained "
                "burn, not a spike)")
    elif serving_stale:
        tail = (" (SERVING-STALE: host(s) "
                f"{sorted(serving_stale)} answer from an older generation "
                "than the bundle dir holds — swap pending or refused)")
    print(f"status[{obs_dir}]: {state}" + tail)
    if corrupt:
        return 3
    return 1 if state == "wedged" else 0


def report_console(url: str, as_json: bool = False) -> int:
    """Client mode for the live run console (rdfind --console-port): fetch
    /status and /progress over HTTP and print the same alive/degrading
    verdict shape as --status, but from the running process itself (exit
    codes: 0 reachable, 1 run wedged per its own heartbeats, 2
    unreachable)."""
    import urllib.error
    import urllib.request
    base = url if "://" in url else "http://" + url
    base = base.rstrip("/")
    try:
        with urllib.request.urlopen(base + "/status", timeout=10) as r:
            status = json.load(r)
        with urllib.request.urlopen(base + "/progress", timeout=10) as r:
            progress = json.load(r)
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"console[{url}]: unreachable ({e})")
        return 2
    if as_json:
        print(json.dumps({"url": base, "status": status,
                          "progress": progress}, sort_keys=True, default=str))
    else:
        hb = status.get("heartbeat") or {}
        state = hb.get("state", "serving")
        where = progress.get("run_stage")
        if progress.get("run_pass") is not None:
            where = f"{where} pass {progress['run_pass']}"
        print(f"console[{base}]: pid {status.get('pid')} {state}, in {where}")
        si = status.get("serving_index")
        if isinstance(si, dict):
            print(f"console[{base}]: index generation "
                  f"{si.get('generation')} (bundle dir has "
                  f"{si.get('bundle_generation')}), {si.get('n_cinds')} "
                  f"CINDs, {si.get('swaps')} swap(s), "
                  f"{si.get('refusals')} refusal(s)")
            fresh = si.get("freshness")
            if isinstance(fresh, dict):
                print(f"console[{base}]: freshness — index age "
                      f"{fresh.get('index_age_s')}s, staleness "
                      f"{fresh.get('staleness_s')}s, "
                      f"{fresh.get('generations_behind')} generation(s) "
                      f"behind")
            slo = status.get("slo")
            if isinstance(slo, dict):
                which = f" ({slo.get('slo')})" if slo.get("slo") else ""
                label = str(slo.get("state", "ok")).upper() \
                    if slo.get("state") != "ok" else "ok"
                print(f"console[{base}]: SLO {label}{which}")
            if si.get("stale"):
                why = (f"; last candidate: {si.get('pending')}"
                       if si.get("pending") else "")
                print(f"console[{base}]: SERVING-STALE — bundle dir "
                      f"committed generation {si.get('bundle_generation')} "
                      f"but the server still answers from "
                      f"{si.get('generation')}{why}")
            for link in si.get("chain") or []:
                print(f"console[{base}]: cert chain gen "
                      f"{link.get('generation')}: output "
                      f"{link.get('output_digest')} (base "
                      f"{link.get('base_output_digest')})")
        util = progress.get("cap_utilization") or {}
        for cap, row in sorted(util.items()):
            if isinstance(row, dict):
                print(f"console[{base}]: cap {cap}: used "
                      f"{row.get('used')}/{row.get('planned')} "
                      f"(frac {row.get('frac')})")
        for cap, adv in sorted((progress.get("cap_forecast") or {}).items()):
            print(f"console[{base}]: DEGRADING — cap {cap} forecast "
                  f"exhausted at pass {adv.get('predicted_pass')}"
                  f"/{adv.get('n_pass')} ({adv.get('reason')})")
    return 1 if (status.get("heartbeat") or {}).get("state") == "wedged" \
        else 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-h", type=float, default=10.0,
                    help="give up after this many hours")
    ap.add_argument("--interval-s", type=float, default=180.0,
                    help="sleep between failed probes")
    ap.add_argument("--status", default=None, metavar="DIR",
                    help="read the heartbeat files in an obs directory (a "
                         "--trace DIR, or this watcher's own "
                         "tpu_watch_obs/) and report alive/wedged/done "
                         "instead of watching")
    ap.add_argument("--stale-s", type=float,
                    default=heartbeat.DEFAULT_STALE_S,
                    help="--status: heartbeat age above which a run counts "
                         "as wedged")
    ap.add_argument("--json", action="store_true",
                    help="--status/--console: emit one machine-readable "
                         "JSON line (state + per-host staleness + "
                         "flight-recorder dump summaries) instead of prose")
    ap.add_argument("--console", default=None, metavar="URL",
                    help="query a live run console (rdfind --console-port) "
                         "at URL (host:port or http://...) instead of "
                         "reading heartbeat files: prints stage/pass, "
                         "per-cap utilization, and any cap-exhaustion "
                         "forecast (degrading ≠ wedged)")
    args = ap.parse_args()
    if args.console is not None:
        return report_console(args.console, as_json=args.json)
    if args.status is not None:
        return report_status(args.status, args.stale_s, as_json=args.json)

    deadline = time.time() + args.deadline_h * 3600
    attempt = 0
    beat(phase="probing")
    while time.time() < deadline:
        if os.path.exists(STOP_FILE):
            log("stop file present; exiting")
            return 0
        attempt += 1
        beat(phase="probing", attempt=attempt)
        log(f"probe attempt {attempt}")
        if probe():
            beat(phase="benching")
            if run_benches():
                log("TPU benches captured; exiting")
                return 0
            log("benches incomplete on a live tunnel; retrying once more "
                "after a short sleep")
            beat(phase="cooldown")
            time.sleep(60)
            if probe() and run_benches():
                log("TPU benches captured on retry; exiting")
                return 0
            log("retry failed; going back to probing")
        beat(phase="sleeping", attempt=attempt)
        time.sleep(args.interval_s)
    log("deadline reached without a live TPU; exiting")
    return 1


if __name__ == "__main__":
    sys.exit(main())
