import time, numpy as np
import rdfind_tpu.models.approximate as ap
import rdfind_tpu.models.allatonce as aa
import rdfind_tpu.models.small_to_large as s2l
from rdfind_tpu.utils.synth import generate_triples
from rdfind_tpu.ops import sketch

triples = generate_triples(100_000, seed=101, n_predicates=18, n_entities=17_000)

for it in range(2):
    stats = {}
    t0=time.perf_counter()
    st = ap.prepare_join_lines(triples, 10, "spo", True, False, stats)
    t1=time.perf_counter(); print(it, "prepare", round(t1-t0,2), flush=True)
    sk = ap._build_sketches(st["line_val_h"], st["line_cap_h"], st["num_caps"], bits=sketch.DEFAULT_BITS, num_hashes=sketch.DEFAULT_HASHES)
    t2=time.perf_counter(); print(it, "sketches", round(t2-t1,2), flush=True)
    frequent = st["dep_count"] >= 10
    cd, cr = ap._candidate_pairs(sk, st["num_caps"], bits=sketch.DEFAULT_BITS, num_hashes=sketch.DEFAULT_HASHES, dep_mask=frequent, ref_mask=frequent)
    t3=time.perf_counter(); print(it, "cand_pairs", round(t3-t2,2), "n_cand", len(cd), flush=True)
    def cooc_fn(dep_ok, ref_ok, key):
        return s2l._chunked_cooc(st["line_val_h"], st["line_cap_h"], dep_ok, ref_ok, aa.PAIR_CHUNK_BUDGET, stats, key)
    d, r, sup = s2l._verify_level(cooc_fn, cd, cr, st["num_caps"], st["dep_count"], st["cap_code"], st["cap_v1"], st["cap_v2"], 10, "pairs_verify")
    t4=time.perf_counter(); print(it, "verify", round(t4-t3,2), "cinds", len(d), flush=True)
