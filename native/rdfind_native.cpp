// Native ingest runtime: multi-file N-Triples/N-Quads reader + tokenizer +
// string interner, exposed as a C API for ctypes.
//
// Plays the role of the reference's JVM ingest infrastructure —
// MultiFileTextInputFormat (rdfind-flink/.../persistence/MultiFileTextInputFormat
// .java:49-368: many files, gz-aware, comment filtering) plus the rdf-converter
// NTriples/NQuads parsers (RDFind.scala:219-237) plus the value dictionary
// (here exact interning, see rdfind_tpu/dictionary.py) — fused into one pass so
// triple ids land directly in an int32 buffer ready for the device pipeline.
//
// Semantics parity with the Python path (rdfind_tpu/io/ntriples.py,
// rdfind_tpu/dictionary.py):
//   * terms keep surface syntax (<iri>, _:blank, "lit"@lang, "lit"^^<t>);
//   * ids are ranks in byte-sorted order of the distinct values, which equals
//     np.unique's code-point order for valid UTF-8;
//   * universal newlines (\n, \r\n, \r), '#' comment lines skipped;
//   * .gz inputs transparently decompressed (zlib gzopen also passes through
//     plain files, so one read path serves both).

#include <zlib.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <numeric>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Ingest {
  // Arena-backed interner: string bytes live in stable deque chunks so the
  // string_view keys stay valid while the map grows.
  std::deque<std::string> arena;
  std::unordered_map<std::string_view, int32_t> intern;
  std::vector<const std::string*> by_id;  // provisional id -> string
  std::vector<int32_t> triples;           // flat (n, 3)
  std::vector<int32_t> remap;             // provisional id -> sorted rank
  std::vector<int64_t> sorted_offsets;    // finalize(): prefix offsets
  int64_t values_bytes = 0;
  std::string error;
  bool finalized = false;

  int32_t intern_token(const char* s, size_t len) {
    std::string_view key(s, len);
    auto it = intern.find(key);
    if (it != intern.end()) return it->second;
    arena.emplace_back(s, len);
    int32_t id = static_cast<int32_t>(by_id.size());
    by_id.push_back(&arena.back());
    intern.emplace(std::string_view(arena.back()), id);
    return id;
  }
};

// --- Tokenizer (mirrors ntriples._scan_term) -------------------------------

struct Term {
  const char* p;
  size_t len;
};

bool is_ws(char c) { return c == ' ' || c == '\t'; }

// Scans one term at line[i]; returns next index or (size_t)-1 on error.
size_t scan_term(const char* line, size_t i, size_t n, Term* out,
                 std::string* err) {
  char c = line[i];
  if (c == '<') {  // IRI
    const char* close =
        static_cast<const char*>(memchr(line + i + 1, '>', n - i - 1));
    if (!close) {
      *err = "unterminated IRI";
      return static_cast<size_t>(-1);
    }
    size_t j = static_cast<size_t>(close - line) + 1;
    *out = {line + i, j - i};
    return j;
  }
  if (c == '"') {  // literal with escapes, optional @lang / ^^<dtype>
    size_t j = i + 1;
    while (j < n) {
      if (line[j] == '\\') {
        j += 2;
        continue;
      }
      if (line[j] == '"') break;
      j++;
    }
    if (j >= n) {
      *err = "unterminated literal";
      return static_cast<size_t>(-1);
    }
    j++;  // past closing quote
    if (j < n && line[j] == '@') {
      while (j < n && !is_ws(line[j])) j++;
    } else if (j + 1 < n && line[j] == '^' && line[j + 1] == '^') {
      j += 2;
      if (j < n && line[j] == '<') {
        const char* close =
            static_cast<const char*>(memchr(line + j + 1, '>', n - j - 1));
        if (!close) {
          *err = "unterminated datatype IRI";
          return static_cast<size_t>(-1);
        }
        j = static_cast<size_t>(close - line) + 1;
      }
    }
    *out = {line + i, j - i};
    return j;
  }
  // blank node / bare token: read to whitespace
  size_t j = i;
  while (j < n && !is_ws(line[j])) j++;
  *out = {line + i, j - i};
  return j;
}

// Parses one line into interned (s, p, o); returns 1 on triple, 0 on blank
// line, -1 on error.
int parse_line(Ingest* ing, const char* line, size_t n, bool tabs,
               bool expect_quad) {
  if (tabs) {
    // split("\t"), need >= 3 fields (parse_tab_line).
    bool blank = true;
    for (size_t k = 0; k < n; k++) {
      if (!is_ws(line[k])) {
        blank = false;
        break;
      }
    }
    if (blank) return 0;
    const char* field = line;
    const char* end = line + n;
    int32_t ids[3];
    int got = 0;
    while (got < 3) {
      const char* tab =
          static_cast<const char*>(memchr(field, '\t', end - field));
      const char* fe = tab ? tab : end;
      ids[got++] = ing->intern_token(field, fe - field);
      if (!tab) break;
      field = tab + 1;
    }
    if (got < 3) {
      ing->error = "expected 3 tab-separated fields";
      return -1;
    }
    ing->triples.insert(ing->triples.end(), ids, ids + 3);
    return 1;
  }
  size_t i = 0;
  int32_t ids[3];
  int got = 0;
  int want = expect_quad ? 4 : 3;
  while (i < n && got < want) {
    while (i < n && is_ws(line[i])) i++;
    if (i >= n || line[i] == '.') break;
    Term t;
    i = scan_term(line, i, n, &t, &ing->error);
    if (i == static_cast<size_t>(-1)) return -1;
    if (got < 3) ids[got] = ing->intern_token(t.p, t.len);
    got++;
  }
  if (got == 0) return 0;
  if (got < 3) {
    ing->error = "expected 3 terms, got " + std::to_string(got);
    return -1;
  }
  ing->triples.insert(ing->triples.end(), ids, ids + 3);
  return 1;
}

}  // namespace

extern "C" {

Ingest* rdf_ingest_new() { return new Ingest(); }

void rdf_ingest_free(Ingest* ing) { delete ing; }

const char* rdf_ingest_error(Ingest* ing) { return ing->error.c_str(); }

// Reads and parses one file; returns triples parsed from it, or -1 on error.
int64_t rdf_ingest_file(Ingest* ing, const char* path, int tabs,
                        int expect_quad, int skip_comments) {
  if (ing->finalized) {
    ing->error = "ingest already finalized";
    return -1;
  }
  gzFile f = gzopen(path, "rb");
  if (!f) {
    ing->error = std::string("cannot open ") + path;
    return -1;
  }
  gzbuffer(f, 1 << 20);
  std::vector<char> buf(1 << 20);
  std::string carry;  // partial line across read chunks
  int64_t count = 0;
  auto handle = [&](const char* line, size_t len) -> bool {
    if (skip_comments && len > 0 && line[0] == '#') return true;
    int rc = parse_line(ing, line, len, tabs != 0, expect_quad != 0);
    if (rc < 0) {
      ing->error += std::string(" in ") + path;
      return false;
    }
    count += rc;
    return true;
  };
  bool ok = true;
  while (ok) {
    int nread = gzread(f, buf.data(), static_cast<unsigned>(buf.size()));
    if (nread < 0) {
      int errnum = 0;
      ing->error = std::string("read error in ") + path + ": " +
                   gzerror(f, &errnum);
      ok = false;
      break;
    }
    if (nread == 0) break;
    const char* p = buf.data();
    const char* end = p + nread;
    while (p < end) {
      const char* nl = p;
      while (nl < end && *nl != '\n' && *nl != '\r') nl++;
      if (nl == end) {  // no terminator in the rest of this chunk
        carry.append(p, end - p);
        break;
      }
      if (!carry.empty()) {
        carry.append(p, nl - p);
        ok = handle(carry.data(), carry.size());
        carry.clear();
      } else {
        ok = handle(p, nl - p);
      }
      if (!ok) break;
      // universal newlines: \r\n counts once
      p = nl + ((*nl == '\r' && nl + 1 < end && nl[1] == '\n') ? 2 : 1);
      // NB: a \r\n split exactly across chunks yields one empty extra line,
      // which parses as blank — harmless.
    }
  }
  if (ok && !carry.empty()) ok = handle(carry.data(), carry.size());
  gzclose(f);
  return ok ? count : -1;
}

// Sorts the dictionary by bytes, remaps triple ids to sorted ranks.
// Returns the number of distinct values.
int64_t rdf_ingest_finalize(Ingest* ing) {
  if (!ing->finalized) {
    size_t nvals = ing->by_id.size();
    std::vector<int32_t> order(nvals);
    std::iota(order.begin(), order.end(), 0);
    std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
      return *ing->by_id[a] < *ing->by_id[b];
    });
    ing->remap.assign(nvals, 0);
    for (size_t rank = 0; rank < nvals; rank++)
      ing->remap[order[rank]] = static_cast<int32_t>(rank);
    for (auto& id : ing->triples) id = ing->remap[id];
    // by_id in sorted order + offsets for export.
    std::vector<const std::string*> sorted(nvals);
    ing->sorted_offsets.assign(nvals + 1, 0);
    int64_t off = 0;
    for (size_t rank = 0; rank < nvals; rank++) {
      sorted[rank] = ing->by_id[order[rank]];
      ing->sorted_offsets[rank] = off;
      off += static_cast<int64_t>(sorted[rank]->size());
    }
    ing->sorted_offsets[nvals] = off;
    ing->values_bytes = off;
    ing->by_id.swap(sorted);
    ing->finalized = true;
  }
  return static_cast<int64_t>(ing->by_id.size());
}

int64_t rdf_ingest_num_triples(Ingest* ing) {
  return static_cast<int64_t>(ing->triples.size() / 3);
}

void rdf_ingest_get_triples(Ingest* ing, int32_t* out) {
  memcpy(out, ing->triples.data(), ing->triples.size() * sizeof(int32_t));
}

int64_t rdf_ingest_values_bytes(Ingest* ing) { return ing->values_bytes; }

// buf receives the concatenated sorted value bytes; offsets receives
// num_values + 1 prefix offsets into buf.
void rdf_ingest_get_values(Ingest* ing, char* buf, int64_t* offsets) {
  if (!ing->finalized) return;
  size_t nvals = ing->by_id.size();
  for (size_t i = 0; i < nvals; i++)
    memcpy(buf + ing->sorted_offsets[i], ing->by_id[i]->data(),
           ing->by_id[i]->size());
  memcpy(offsets, ing->sorted_offsets.data(),
         (nvals + 1) * sizeof(int64_t));
}

}  // extern "C"
