// Native ingest runtime: multi-file N-Triples/N-Quads reader + tokenizer +
// string interner, exposed as a C API for ctypes.
//
// Plays the role of the reference's JVM ingest infrastructure —
// MultiFileTextInputFormat (rdfind-flink/.../persistence/MultiFileTextInputFormat
// .java:49-368: many files, gz-aware, comment filtering) plus the rdf-converter
// NTriples/NQuads parsers (RDFind.scala:219-237) plus the value dictionary
// (here exact interning, see rdfind_tpu/dictionary.py) — fused into one pass so
// triple ids land directly in an int32 buffer ready for the device pipeline.
//
// Two execution modes share one handle type:
//
//   * the SERIAL path (rdf_ingest_file + rdf_ingest_finalize): one thread,
//     one interner, byte-sort + remap at the end.  This is the reference
//     implementation of the id contract below and stays deliberately simple
//     (its scalar scan is the differential oracle for the SWAR fast path).
//   * the PARALLEL STREAMING path (rdf_ingest_begin / rdf_ingest_next_block /
//     rdf_ingest_stream_finish): a work-stealing unit queue feeding N worker
//     threads, each with its own arena-backed interner emitting provisional
//     thread-local ids.  Committed unit blocks stream to the caller IN UNIT
//     ORDER while later units still parse; the finish step hash-partitions
//     the per-thread interners into S shards (crc32 % S — the SAME partition
//     function as the multi-host dictionary, rdfind_tpu/dictionary.py:
//     value_shard), dedupes each shard in parallel, S-way-merges the
//     shard-sorted runs into the byte-sorted global rank order, and exports
//     per-thread local→global remap tables for the caller to rewrite its
//     streamed blocks.
//
// The byte-level hot loop runs three speed rungs (each independently
// switchable via rdf_ingest_set_opts, all bit-identical to the scalar
// reference by construction):
//
//   1. SWAR scanning: newline / field / literal delimiters are found 8 bytes
//      at a time with the zero-byte trick ((x - 0x0101..) & ~x & 0x8080..);
//      a scalar loop finishes the tail, so CRLF / comment / quad edge cases
//      see exactly the bytes the scalar path sees.
//   2. mmap zero-copy: plain files are mapped once per handle and interners
//      store (ptr, len) views INTO the mapping — term bytes are copied only
//      when a distinct value first enters the arena from a transient buffer
//      (gz output, fread chunks, subtask buffers).  Mappings outlive
//      finalize: the exported sorted values view them directly.
//   3. Parallel gzip: multi-member .gz files are split at exact member
//      boundaries (cheap magic-candidate scan, then an inflate pass that
//      records the consumed offset at each Z_STREAM_END — candidates alone
//      are not trustworthy) and the members fan out onto the unit queue;
//      a large single-member .gz gets a two-stage decode→parse pipeline:
//      a decoder thread inflates into newline-snapped chunk buffers pushed
//      onto a bounded subtask queue that idle workers (and the unit's own
//      leader) parse concurrently, delivered to the caller in chunk order.
//
// The id contract (ALL paths, bit-identical by construction):
//   * terms keep surface syntax (<iri>, _:blank, "lit"@lang, "lit"^^<t>);
//   * ids are ranks in byte-sorted order of the distinct values, which equals
//     np.unique's code-point order for valid UTF-8;
//   * triples keep input order (file order, then line order; a split plain
//     file's chunks and a split gz's members/subtasks are delivered in
//     offset order);
//   * universal newlines (\n, \r\n, \r), '#' comment lines skipped;
//   * .gz inputs transparently decompressed; gzip content is detected by
//     magic sniff as well as extension (zlib gzopen also passes through
//     plain files, so one stream path serves both).
//
// Chunk ownership rule (Hadoop-style line splits): a chunk [o, e) with o > 0
// first discards bytes through the first line terminator at/after o, then
// parses every line whose first byte starts at position <= e (reading past e
// to finish its last line).  A line starting exactly at e belongs to the
// chunk ENDING at e; the next chunk's unconditional discard drops it.  Every
// line is therefore parsed exactly once, for any chunking.

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <zlib.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <numeric>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

int64_t ns_since(Clock::time_point t0) {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(Clock::now() -
                                                              t0)
      .count();
}

// --- SWAR primitives -------------------------------------------------------
//
// The ctz-based first-match index assumes little-endian byte order; on a
// big-endian build the word loop compiles out and every find falls through
// to the scalar tail, which is always correct.
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
#define RDF_SWAR_LE 1
#else
#define RDF_SWAR_LE 0
#endif

constexpr uint64_t kOnes = 0x0101010101010101ull;
constexpr uint64_t kHigh = 0x8080808080808080ull;

inline uint64_t load64(const char* p) {
  uint64_t w;
  memcpy(&w, p, 8);
  return w;
}

// High bit set in every byte of x that was zero.
inline uint64_t zero_bytes(uint64_t x) { return (x - kOnes) & ~x & kHigh; }

// First byte in [p, end) equal to a or b; end if absent.
inline const char* find2(const char* p, const char* end, char a, char b,
                         bool swar) {
#if RDF_SWAR_LE
  if (swar) {
    const uint64_t ba = kOnes * static_cast<uint8_t>(a);
    const uint64_t bb = kOnes * static_cast<uint8_t>(b);
    while (end - p >= 8) {
      uint64_t w = load64(p);
      uint64_t hit = zero_bytes(w ^ ba) | zero_bytes(w ^ bb);
      if (hit) return p + (__builtin_ctzll(hit) >> 3);
      p += 8;
    }
  }
#else
  (void)swar;
#endif
  while (p < end && *p != a && *p != b) p++;
  return p;
}

inline const char* find_eol(const char* p, const char* end, bool swar) {
  return find2(p, end, '\n', '\r', swar);
}

// 64-bit bytes hash for the interner's open-addressing table (murmur-style
// finalizer over 8-byte SWAR strides).  Ids never depend on this hash —
// they are first-occurrence insertion order — so any mixing change is
// output-invisible.
inline uint64_t hash_bytes(const char* p, size_t n) {
  uint64_t h = 0x9e3779b97f4a7c15ull ^ static_cast<uint64_t>(n);
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    h = (h ^ load64(p + i)) * 0xff51afd7ed558ccdull;
    h ^= h >> 29;
  }
  if (i < n) {
    uint64_t w = 0;
    memcpy(&w, p + i, n - i);
    h = (h ^ w) * 0xc4ceb9fe1a85ec53ull;
    h ^= h >> 29;
  }
  h *= 0xff51afd7ed558ccdull;
  h ^= h >> 32;
  return h;
}

// Per-phase ingest telemetry (exported via rdf_ingest_stats/stats2).
// Worker-side counters are atomics (summed across threads); merge-stage and
// begin-stage counters are written single-threaded.
struct Stats {
  std::atomic<int64_t> bytes_read{0};  // post-decompression bytes parsed
  std::atomic<int64_t> read_ns{0};     // plain-file fread time
  std::atomic<int64_t> decode_ns{0};   // gz read+inflate time (zlib fuses them)
  std::atomic<int64_t> parse_ns{0};    // tokenize+intern (unit wall - I/O)
  int64_t intern_ns = 0;               // shard dedupe+sort (dictionary build)
  int64_t merge_ns = 0;                // partition + global rank merge
  int64_t remap_ns = 0;                // local->global table construction
  std::atomic<int64_t> queue_stalls{0};  // next_block waits that blocked
  std::atomic<int64_t> stall_ns{0};      // total blocked time in next_block
  std::atomic<int64_t> n_subtasks{0};    // pipelined gz chunks emitted
  int64_t mmap_bytes = 0;                // input bytes served zero-copy
  int64_t n_members = 0;                 // gz members split onto the queue
  int64_t n_units = 0;
  int64_t n_files = 0;
  int n_threads = 1;
};

// Interner: open-addressing hash table over (ptr, len) value views.  Stable
// bytes (mmap-backed) are referenced in place; transient bytes (gz output,
// fread buffers) are copied into stable deque arena chunks first.  One per
// handle on the serial path; one per worker thread on the parallel path.
struct Interner {
  std::deque<std::string> arena;         // owned bytes for transient inputs
  std::vector<std::string_view> by_id;   // provisional id -> value bytes
  // Power-of-two open addressing: keys live in by_id; probes compare the
  // stored 64-bit hash first and memcmp only on hash match.
  std::vector<uint64_t> slot_hash;
  std::vector<int32_t> slot_id;
  size_t mask = 0;
  size_t grow_at = 0;

  Interner() { rehash(1 << 12); }

  void rehash(size_t cap) {
    std::vector<uint64_t> oh = std::move(slot_hash);
    std::vector<int32_t> oi = std::move(slot_id);
    slot_hash.assign(cap, 0);
    slot_id.assign(cap, -1);
    mask = cap - 1;
    grow_at = cap - cap / 4;  // resize at 3/4 load
    for (size_t s = 0; s < oi.size(); s++) {
      if (oi[s] < 0) continue;
      size_t j = oh[s] & mask;
      while (slot_id[j] >= 0) j = (j + 1) & mask;
      slot_hash[j] = oh[s];
      slot_id[j] = oi[s];
    }
  }

  int32_t intern_token(const char* s, size_t len, bool stable) {
    uint64_t h = hash_bytes(s, len);
    size_t j = h & mask;
    while (slot_id[j] >= 0) {
      if (slot_hash[j] == h) {
        std::string_view v = by_id[slot_id[j]];
        if (v.size() == len && memcmp(v.data(), s, len) == 0)
          return slot_id[j];
      }
      j = (j + 1) & mask;
    }
    const char* bytes = s;
    if (!stable) {
      arena.emplace_back(s, len);
      bytes = arena.back().data();
    }
    int32_t id = static_cast<int32_t>(by_id.size());
    by_id.emplace_back(bytes, len);
    slot_hash[j] = h;
    slot_id[j] = id;
    if (by_id.size() >= grow_at) rehash((mask + 1) * 2);
    return id;
  }
};

// Everything one parsed line needs: where ids come from, where triples go,
// where errors land, and which scan mode / byte-stability applies.
struct ParseCtx {
  Interner* in;
  std::vector<int32_t>* triples;
  std::string* error;
  bool swar = true;    // SWAR delimiter scanning (scalar oracle when false)
  bool stable = false; // line bytes outlive the handle (mmap-backed)
};

struct Parallel;  // fwd

struct Mapping {
  void* addr;
  size_t len;
};

struct Ingest {
  Interner dict;                  // serial-path interner
  std::vector<int32_t> triples;   // serial path: flat (n, 3)
  std::vector<int32_t> remap;     // serial path: provisional id -> rank
  // Export representation shared by both paths after finalize/stream_finish:
  std::vector<std::string_view> sorted_vals;  // byte-sorted distinct values
  std::vector<int64_t> sorted_offsets;        // prefix offsets
  int64_t values_bytes = 0;
  std::string error;
  bool finalized = false;
  Stats stats;
  // Speed-rung knobs (rdf_ingest_set_opts; resolved Python-side from env).
  bool opt_swar = true;
  bool opt_mmap = true;
  bool opt_gz_pipeline = true;
  int64_t opt_gz_chunk = 8ll << 20;  // decoded bytes per pipelined subtask
  // File mappings live as long as the handle: interner views and the
  // exported sorted values point into them.
  std::vector<Mapping> mappings;
  std::unordered_map<std::string, const char*> mapped_by_path;
  std::unique_ptr<Parallel> par;  // non-null once rdf_ingest_begin ran

  const char* map_file(const std::string& path, int64_t size) {
    auto it = mapped_by_path.find(path);
    if (it != mapped_by_path.end()) return it->second;
    int fd = open(path.c_str(), O_RDONLY);
    if (fd < 0) return nullptr;
    void* a = mmap(nullptr, static_cast<size_t>(size), PROT_READ, MAP_PRIVATE,
                   fd, 0);
    close(fd);
    if (a == MAP_FAILED) return nullptr;
#ifdef MADV_SEQUENTIAL
    (void)madvise(a, static_cast<size_t>(size), MADV_SEQUENTIAL);
#endif
    mappings.push_back({a, static_cast<size_t>(size)});
    mapped_by_path.emplace(path, static_cast<const char*>(a));
    return static_cast<const char*>(a);
  }

  ~Ingest();
};

// --- Tokenizer (mirrors ntriples._scan_term) -------------------------------

struct Term {
  const char* p;
  size_t len;
};

bool is_ws(char c) { return c == ' ' || c == '\t'; }

// Scans one term at line[i]; returns next index or (size_t)-1 on error.
size_t scan_term(const char* line, size_t i, size_t n, Term* out,
                 std::string* err, bool swar) {
  char c = line[i];
  if (c == '<') {  // IRI
    const char* close =
        static_cast<const char*>(memchr(line + i + 1, '>', n - i - 1));
    if (!close) {
      *err = "unterminated IRI";
      return static_cast<size_t>(-1);
    }
    size_t j = static_cast<size_t>(close - line) + 1;
    *out = {line + i, j - i};
    return j;
  }
  if (c == '"') {  // literal with escapes, optional @lang / ^^<dtype>
    const char* end = line + n;
    const char* q = line + i + 1;
    while (true) {
      if (q >= end) {
        *err = "unterminated literal";
        return static_cast<size_t>(-1);
      }
      q = find2(q, end, '"', '\\', swar);
      if (q == end) {
        *err = "unterminated literal";
        return static_cast<size_t>(-1);
      }
      if (*q == '"') break;
      q += 2;  // skip the escape pair, keep scanning
    }
    size_t j = static_cast<size_t>(q - line) + 1;  // past closing quote
    if (j < n && line[j] == '@') {
      j = static_cast<size_t>(find2(line + j, line + n, ' ', '\t', swar) -
                              line);
    } else if (j + 1 < n && line[j] == '^' && line[j + 1] == '^') {
      j += 2;
      if (j < n && line[j] == '<') {
        const char* close =
            static_cast<const char*>(memchr(line + j + 1, '>', n - j - 1));
        if (!close) {
          *err = "unterminated datatype IRI";
          return static_cast<size_t>(-1);
        }
        j = static_cast<size_t>(close - line) + 1;
      }
    }
    *out = {line + i, j - i};
    return j;
  }
  // blank node / bare token: read to whitespace
  size_t j =
      static_cast<size_t>(find2(line + i, line + n, ' ', '\t', swar) - line);
  *out = {line + i, j - i};
  return j;
}

// Parses one line into interned (s, p, o); returns 1 on triple, 0 on blank
// line, -1 on error.
int parse_line(ParseCtx* ctx, const char* line, size_t n, bool tabs,
               bool expect_quad) {
  if (tabs) {
    // split("\t"), need >= 3 fields (parse_tab_line).
    bool blank = true;
    for (size_t k = 0; k < n; k++) {
      if (!is_ws(line[k])) {
        blank = false;
        break;
      }
    }
    if (blank) return 0;
    const char* field = line;
    const char* end = line + n;
    int32_t ids[3];
    int got = 0;
    while (got < 3) {
      const char* tab =
          static_cast<const char*>(memchr(field, '\t', end - field));
      const char* fe = tab ? tab : end;
      ids[got++] = ctx->in->intern_token(field, fe - field, ctx->stable);
      if (!tab) break;
      field = tab + 1;
    }
    if (got < 3) {
      *ctx->error = "expected 3 tab-separated fields";
      return -1;
    }
    ctx->triples->insert(ctx->triples->end(), ids, ids + 3);
    return 1;
  }
  size_t i = 0;
  int32_t ids[3];
  int got = 0;
  int want = expect_quad ? 4 : 3;
  while (i < n && got < want) {
    while (i < n && is_ws(line[i])) i++;
    if (i >= n || line[i] == '.') break;
    Term t;
    i = scan_term(line, i, n, &t, ctx->error, ctx->swar);
    if (i == static_cast<size_t>(-1)) return -1;
    if (got < 3) ids[got] = ctx->in->intern_token(t.p, t.len, ctx->stable);
    got++;
  }
  if (got == 0) return 0;
  if (got < 3) {
    *ctx->error = "expected 3 terms, got " + std::to_string(got);
    return -1;
  }
  ctx->triples->insert(ctx->triples->end(), ids, ids + 3);
  return 1;
}

// --- Byte sources ----------------------------------------------------------

// Sequential decoded-byte reader: one interface serves gzopen streams (gz
// files and plain passthrough) and single raw gzip members, so the line
// streamer and the pipeline decoder share one read loop.
struct ByteSource {
  virtual int64_t read(char* dst, int64_t cap) = 0;  // >0 bytes, 0 EOF, <0 err
  virtual std::string error_detail() const = 0;
  virtual ~ByteSource() {}
};

struct GzSource : ByteSource {
  gzFile f = nullptr;
  std::string err;
  explicit GzSource(const char* path) {
    f = gzopen(path, "rb");
    if (f) gzbuffer(f, 1 << 20);
  }
  bool ok() const { return f != nullptr; }
  int64_t read(char* dst, int64_t cap) override {
    int n = gzread(f, dst, static_cast<unsigned>(cap));
    if (n < 0) {
      int errnum = 0;
      err = gzerror(f, &errnum);
    }
    return n;
  }
  std::string error_detail() const override { return err; }
  ~GzSource() {
    if (f) gzclose(f);
  }
};

// Inflates exactly ONE gzip member occupying [off, off+len) of path (raw
// inflate with the gzip wrapper; stops at Z_STREAM_END).
struct MemberSource : ByteSource {
  FILE* f = nullptr;
  z_stream strm{};
  std::vector<char> inbuf;
  int64_t remaining;
  bool stream_end = false;
  bool inited = false;
  std::string err;
  MemberSource(const char* path, int64_t off, int64_t len)
      : inbuf(1 << 18), remaining(len) {
    f = fopen(path, "rb");
    if (!f) return;
    if (off > 0 && fseek(f, static_cast<long>(off), SEEK_SET) != 0) {
      fclose(f);
      f = nullptr;
      return;
    }
    if (inflateInit2(&strm, 16 + MAX_WBITS) != Z_OK) {
      fclose(f);
      f = nullptr;
      return;
    }
    inited = true;
  }
  bool ok() const { return f != nullptr; }
  int64_t read(char* dst, int64_t cap) override {
    if (stream_end) return 0;
    strm.next_out = reinterpret_cast<Bytef*>(dst);
    strm.avail_out = static_cast<uInt>(cap);
    while (strm.avail_out > 0) {
      if (strm.avail_in == 0 && remaining > 0) {
        size_t want = static_cast<size_t>(
            std::min<int64_t>(static_cast<int64_t>(inbuf.size()), remaining));
        size_t n = fread(inbuf.data(), 1, want, f);
        if (n == 0) {
          err = "truncated gzip member";
          return -1;
        }
        remaining -= static_cast<int64_t>(n);
        strm.next_in = reinterpret_cast<Bytef*>(inbuf.data());
        strm.avail_in = static_cast<uInt>(n);
      }
      int rc = inflate(&strm, Z_NO_FLUSH);
      if (rc == Z_STREAM_END) {
        stream_end = true;
        break;
      }
      if (rc != Z_OK && rc != Z_BUF_ERROR) {
        err = "corrupt gzip member";
        return -1;
      }
      if (strm.avail_in == 0 && remaining == 0) {
        err = "truncated gzip member";
        return -1;
      }
    }
    return cap - static_cast<int64_t>(strm.avail_out);
  }
  std::string error_detail() const override { return err; }
  ~MemberSource() {
    if (inited) inflateEnd(&strm);
    if (f) fclose(f);
  }
};

// --- Line streaming --------------------------------------------------------

// Streams universal-newline lines from a ByteSource into
// handle(line, len) -> bool.  Returns false on read error or handle failure
// (err set).  io_ns/bytes accumulate read+decode telemetry.
template <typename H>
bool for_stream_lines(ByteSource& src, const char* path, bool swar,
                      std::string* err, H&& handle, int64_t* io_ns,
                      int64_t* bytes_read) {
  std::vector<char> buf(1 << 20);
  std::string carry;  // partial line across read chunks
  bool ok = true;
  while (ok) {
    auto t0 = Clock::now();
    int64_t nread = src.read(buf.data(), static_cast<int64_t>(buf.size()));
    *io_ns += ns_since(t0);
    if (nread < 0) {
      *err = std::string("read error in ") + path + ": " + src.error_detail();
      return false;
    }
    if (nread == 0) break;
    *bytes_read += nread;
    const char* p = buf.data();
    const char* end = p + nread;
    while (p < end) {
      const char* nl = find_eol(p, end, swar);
      if (nl == end) {  // no terminator in the rest of this chunk
        carry.append(p, end - p);
        break;
      }
      if (!carry.empty()) {
        carry.append(p, nl - p);
        ok = handle(carry.data(), carry.size());
        carry.clear();
      } else {
        ok = handle(p, nl - p);
      }
      if (!ok) break;
      // universal newlines: \r\n counts once
      p = nl + ((*nl == '\r' && nl + 1 < end && nl[1] == '\n') ? 2 : 1);
      // NB: a \r\n split exactly across chunks yields one empty extra line,
      // which parses as blank — harmless.
    }
  }
  if (ok && !carry.empty()) ok = handle(carry.data(), carry.size());
  return ok;
}

// Streams the lines OWNED by byte range [off, off+len) of a plain file (see
// the chunk ownership rule in the header comment) into handle().  The fread
// path: used when mmap is disabled or failed.
template <typename H>
bool for_chunk_lines(const char* path, int64_t off, int64_t len, bool swar,
                     std::string* err, H&& handle, int64_t* read_ns,
                     int64_t* bytes_read) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    *err = std::string("cannot open ") + path;
    return false;
  }
  if (off > 0 && fseek(f, static_cast<long>(off), SEEK_SET) != 0) {
    *err = std::string("cannot seek in ") + path;
    fclose(f);
    return false;
  }
  const int64_t end = off + len;  // lines starting at pos <= end are ours
  std::vector<char> buf(1 << 20);
  std::string carry;
  bool discard = off > 0;  // drop through the first terminator (prev owns it)
  bool pending_cr = false;  // '\r' consumed at buffer end; eat a leading '\n'
  int64_t pos = off;        // absolute offset of the next unread byte
  int64_t line_start = off;
  bool ok = true;
  bool done = false;
  while (ok && !done) {
    // Read the owned range in full-buffer strides, then finish the final
    // line in small tail reads — the overshoot past `end` stays bounded by
    // one tail stride instead of a whole buffer.
    size_t want = buf.size();
    if (pos <= end)
      want = static_cast<size_t>(
          std::min<int64_t>(static_cast<int64_t>(want), end - pos + 1));
    else
      want = 4096;
    auto t0 = Clock::now();
    size_t nread = fread(buf.data(), 1, want, f);
    *read_ns += ns_since(t0);
    if (nread == 0) break;  // EOF (or error: tail handled below)
    *bytes_read += static_cast<int64_t>(nread);
    const char* p = buf.data();
    const char* bend = p + nread;
    if (pending_cr) {
      pending_cr = false;
      if (*p == '\n') {
        p++;
        pos++;
        line_start = pos;
        if (line_start > end) {
          done = true;
          break;
        }
      }
    }
    while (p < bend) {
      const char* nl = find_eol(p, bend, swar);
      if (nl == bend) {
        if (!discard) carry.append(p, bend - p);
        pos += bend - p;
        break;
      }
      if (discard) {
        discard = false;
      } else if (!carry.empty()) {
        carry.append(p, nl - p);
        ok = handle(carry.data(), carry.size());
        carry.clear();
      } else {
        ok = handle(p, nl - p);
      }
      if (!ok) break;
      int64_t term = 1;
      if (*nl == '\r') {
        if (nl + 1 < bend) {
          if (nl[1] == '\n') term = 2;
        } else {
          pending_cr = true;  // resolve against the next refill
        }
      }
      pos += (nl - p) + term;
      p = nl + term;
      line_start = pos;
      if (line_start > end) {
        done = true;
        break;
      }
    }
  }
  if (ok && !done && !discard && !carry.empty() && line_start <= end)
    ok = handle(carry.data(), carry.size());  // final unterminated line
  fclose(f);
  return ok;
}

// Streams the lines OWNED by [off, off+len) of a fully in-memory buffer
// (an mmap'd file, or one decoded pipeline subtask with off=0, len=size).
// Same ownership rule as for_chunk_lines, but zero-copy: handle() sees
// views into the buffer, and the final line of a chunk simply reads past
// `end` — no carry string, no pending-CR state.
template <typename H>
bool for_mapped_lines(const char* data, int64_t size, int64_t off,
                      int64_t len, bool swar, H&& handle,
                      int64_t* bytes_read) {
  const char* const eof = data + size;
  const int64_t end = off + len;  // lines starting at pos <= end are ours
  const char* p = data + off;
  if (off > 0) {  // discard through the first terminator (prev chunk owns it)
    const char* nl = find_eol(p, eof, swar);
    if (nl == eof) {  // chunk is the tail of the previous chunk's last line
      *bytes_read += eof - p;
      return true;
    }
    p = nl + ((*nl == '\r' && nl + 1 < eof && nl[1] == '\n') ? 2 : 1);
  }
  bool ok = true;
  while (ok && p < eof && (p - data) <= end) {
    const char* nl = find_eol(p, eof, swar);
    ok = handle(p, static_cast<size_t>(nl - p));
    if (nl == eof) {  // final unterminated line
      p = eof;
      break;
    }
    p = nl + ((*nl == '\r' && nl + 1 < eof && nl[1] == '\n') ? 2 : 1);
  }
  *bytes_read += p - (data + off);
  return ok;
}

// --- gz member discovery ---------------------------------------------------

// Exact member boundaries of a multi-member gzip file, or an empty vector
// when the file is single-member / unreadable / not worth splitting.  Two
// passes: a cheap scan for gzip magic candidates (1f 8b 08 with sane flag
// bits — NOT trustworthy, the magic can occur inside compressed data), then,
// only if a candidate exists, an inflate-discard pass recording the consumed
// input offset at each Z_STREAM_END — the only exact answer.  Any decode
// trouble returns empty so the normal single-unit path surfaces the error
// with the serial path's message.
std::vector<std::pair<int64_t, int64_t>> scan_gz_members(const char* path,
                                                         int64_t size) {
  std::vector<std::pair<int64_t, int64_t>> out;
  FILE* f = fopen(path, "rb");
  if (!f) return out;
  bool candidate = false;
  {
    std::vector<char> buf(1 << 20);
    int64_t base = 0;
    size_t carry = 0;
    while (!candidate) {
      size_t n = fread(buf.data() + carry, 1, buf.size() - carry, f);
      if (n == 0) break;
      size_t avail = carry + n;
      for (size_t i = 0; i + 4 <= avail; i++) {
        if (static_cast<unsigned char>(buf[i]) == 0x1f &&
            static_cast<unsigned char>(buf[i + 1]) == 0x8b &&
            static_cast<unsigned char>(buf[i + 2]) == 0x08 &&
            (static_cast<unsigned char>(buf[i + 3]) & 0xe0) == 0 &&
            base + static_cast<int64_t>(i) > 0) {
          candidate = true;
          break;
        }
      }
      size_t keep = avail >= 3 ? 3 : avail;
      memmove(buf.data(), buf.data() + avail - keep, keep);
      base += static_cast<int64_t>(avail - keep);
      carry = keep;
    }
  }
  if (!candidate) {
    fclose(f);
    return out;
  }
  if (fseek(f, 0, SEEK_SET) != 0) {
    fclose(f);
    return out;
  }
  z_stream strm{};
  if (inflateInit2(&strm, 16 + MAX_WBITS) != Z_OK) {
    fclose(f);
    return out;
  }
  std::vector<char> in(1 << 20), scratch(1 << 20);
  std::vector<int64_t> starts{0};
  int64_t fed = 0;
  bool fail = false;
  while (!fail) {
    if (strm.avail_in == 0) {
      size_t n = fread(in.data(), 1, in.size(), f);
      if (n == 0 && fed >= size) {
        fail = true;  // ran off the end without a final Z_STREAM_END
        break;
      }
      if (n == 0) {
        fail = true;
        break;
      }
      fed += static_cast<int64_t>(n);
      strm.next_in = reinterpret_cast<Bytef*>(in.data());
      strm.avail_in = static_cast<uInt>(n);
    }
    strm.next_out = reinterpret_cast<Bytef*>(scratch.data());
    strm.avail_out = static_cast<uInt>(scratch.size());
    int rc = inflate(&strm, Z_NO_FLUSH);
    if (rc == Z_STREAM_END) {
      int64_t consumed = fed - static_cast<int64_t>(strm.avail_in);
      if (consumed >= size) break;  // final member
      starts.push_back(consumed);
      if (inflateReset(&strm) != Z_OK) fail = true;
      continue;
    }
    if (rc != Z_OK && rc != Z_BUF_ERROR) fail = true;
  }
  inflateEnd(&strm);
  fclose(f);
  if (fail || starts.size() < 2) return out;
  for (size_t i = 0; i < starts.size(); i++) {
    int64_t end = (i + 1 < starts.size()) ? starts[i + 1] : size;
    out.emplace_back(starts[i], end - starts[i]);
  }
  return out;
}

// --- Parallel streaming engine ---------------------------------------------

enum UnitKind {
  K_STREAM,  // whole file via gzopen (gz single-member, or plain fallback)
  K_CHUNK,   // plain-file byte range via fread (mmap off/failed)
  K_MMAP,    // plain-file byte range via the handle's mapping (zero-copy)
  K_MEMBER,  // one gzip member: raw inflate of [off, off+len)
};

struct Unit {
  std::string path;
  UnitKind kind = K_STREAM;
  int64_t off = 0;
  int64_t len = 0;   // byte range (chunks/members) or file size (K_STREAM)
  bool is_gz = false;           // gzip content (extension or magic sniff)
  const char* map = nullptr;    // K_MMAP: base of the whole-file mapping
  int64_t map_size = 0;
};

// One decoded chunk of a pipelined gz unit, parsed by whichever worker pops
// it off the subtask queue; delivered to the caller in chunk order.
struct SubBlock {
  std::vector<int32_t> triples;  // provisional thread-local ids
  int thread = -1;
  std::string error;
  bool done = false;  // guarded by Parallel::mu
};

struct Subtask {
  size_t unit = 0;
  size_t idx = 0;     // index into results[unit].subs
  std::string data;   // decoded bytes, newline-snapped
};

struct UnitResult {
  std::vector<int32_t> triples;  // provisional thread-local ids
  int thread = -1;
  std::string error;
  bool skipped = false;  // queued after a failed unit; never delivered
  // Pipelined gz delivery state (all guarded by Parallel::mu):
  bool pipelined = false;
  bool decoder_done = false;
  std::deque<SubBlock> subs;           // grows as the decoder emits
  size_t n_subs_final = 0;             // valid once decoder_done
  size_t next_sub = 0;                 // delivery cursor
};

struct ThreadShard {
  Interner in;
  std::vector<int32_t> to_global;  // local id -> byte-sorted global rank
  // Per-merge-shard local-id buckets (filled by the partition stage, read by
  // the dedupe and remap stages).
  std::vector<std::vector<int32_t>> buckets;
};

struct Parallel {
  std::vector<Unit> units;
  std::vector<UnitResult> results;
  std::vector<std::unique_ptr<ThreadShard>> shards;  // one per worker thread
  std::vector<std::thread> workers;
  std::atomic<size_t> next_unit{0};
  // First failed unit index: workers skip units queued after it (best-effort
  // cancellation; earlier units still complete so in-order delivery reaches
  // the failure deterministically — the same "first error wins" surface as
  // the serial path).
  std::atomic<int64_t> abort_after{INT64_MAX};
  std::mutex mu;
  std::condition_variable cv;
  std::vector<char> done;  // guarded by mu
  size_t next_deliver = 0;
  std::vector<int32_t>* cur_triples = nullptr;  // last delivered block
  int cur_thread = -1;
  bool tabs = false, quad = false, skip_comments = true;
  bool swar = true, gz_pipeline = true;
  int64_t gz_chunk = 8ll << 20;
  // Decode→parse pipeline state (guarded by mu): decoders block while the
  // queue is at capacity; workers that run out of units drain it.
  std::deque<Subtask> subq;
  size_t subq_cap = 8;
  int active_pipelines = 0;
  bool joined = false;
  bool drained = false;

  void join_workers() {
    if (joined) return;
    for (auto& t : workers)
      if (t.joinable()) t.join();
    joined = true;
  }
  ~Parallel() { join_workers(); }
};

Ingest::~Ingest() {
  par.reset();  // joins workers before the mappings they read go away
  for (auto& m : mappings) munmap(m.addr, m.len);
}

void abort_at(Parallel* p, size_t u) {
  int64_t cur = p->abort_after.load();
  while (static_cast<int64_t>(u) < cur &&
         !p->abort_after.compare_exchange_weak(cur, static_cast<int64_t>(u)))
    ;
}

// Parses one decoded subtask buffer into its SubBlock slot.
void parse_subtask(Parallel* p, Subtask&& st, int thread_idx, Stats* stats) {
  UnitResult* res = &p->results[st.unit];
  SubBlock* sb;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    sb = &res->subs[st.idx];  // deque: stable across concurrent push_back
  }
  std::vector<int32_t> triples;
  std::string err;
  if (p->abort_after.load() >= static_cast<int64_t>(st.unit)) {
    const Unit& u = p->units[st.unit];
    ThreadShard* sh = p->shards[thread_idx].get();
    ParseCtx ctx{&sh->in, &triples, &err, p->swar, /*stable=*/false};
    auto handle = [&](const char* line, size_t len) -> bool {
      if (p->skip_comments && len > 0 && line[0] == '#') return true;
      int rc = parse_line(&ctx, line, len, p->tabs, p->quad);
      if (rc < 0) {
        err += std::string(" in ") + u.path;
        return false;
      }
      return true;
    };
    int64_t dummy = 0;
    auto t0 = Clock::now();
    bool ok = for_mapped_lines(st.data.data(),
                               static_cast<int64_t>(st.data.size()), 0,
                               static_cast<int64_t>(st.data.size()), p->swar,
                               handle, &dummy);
    stats->parse_ns += ns_since(t0);
    if (!ok) abort_at(p, st.unit);
  }
  {
    std::lock_guard<std::mutex> lk(p->mu);
    sb->triples = std::move(triples);
    sb->thread = thread_idx;
    if (!err.empty()) sb->error = err;
    sb->done = true;
  }
  p->cv.notify_all();
}

// Largest prefix of s ending exactly after a line terminator (a '\n', or a
// '\r' that is provably not the first half of a straddling \r\n); 0 when no
// safe split point exists yet.
size_t split_point(const std::string& s) {
  for (size_t i = s.size(); i-- > 0;) {
    if (s[i] == '\n') return i + 1;
    if (s[i] == '\r' && i + 1 < s.size())
      return i + (s[i + 1] == '\n' ? 2 : 1);
  }
  return 0;
}

void emit_sub(Parallel* p, UnitResult* res, size_t u, size_t idx,
              std::string&& data, Stats* stats) {
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv.wait(lk, [&] { return p->subq.size() < p->subq_cap; });
    res->subs.emplace_back();
    p->subq.push_back(Subtask{u, idx, std::move(data)});
  }
  stats->n_subtasks++;
  p->cv.notify_all();
}

// Decoder half of the two-stage gz pipeline: inflate into newline-snapped
// chunk buffers and feed the bounded subtask queue.
void decoder_main(Parallel* p, size_t u, UnitResult* res, Stats* stats) {
  const Unit& unit = p->units[u];
  std::string derr;
  size_t emitted = 0;
  std::unique_ptr<ByteSource> src;
  if (unit.kind == K_MEMBER) {
    auto ms = std::make_unique<MemberSource>(unit.path.c_str(), unit.off,
                                             unit.len);
    if (ms->ok()) src = std::move(ms);
  } else {
    auto gs = std::make_unique<GzSource>(unit.path.c_str());
    if (gs->ok()) src = std::move(gs);
  }
  if (!src) {
    derr = std::string("cannot open ") + unit.path;
  } else {
    const int64_t chunk = std::max<int64_t>(p->gz_chunk, 256);
    std::vector<char> buf(
        static_cast<size_t>(std::min<int64_t>(chunk, 1 << 20)));
    std::string pend;
    while (true) {
      if (p->abort_after.load() < static_cast<int64_t>(u)) {
        pend.clear();  // cancelled: this unit will never be delivered
        break;
      }
      auto t0 = Clock::now();
      int64_t n = src->read(buf.data(), static_cast<int64_t>(buf.size()));
      stats->decode_ns += ns_since(t0);
      if (n < 0) {
        derr = std::string("read error in ") + unit.path + ": " +
               src->error_detail();
        break;
      }
      if (n == 0) break;
      stats->bytes_read += n;
      pend.append(buf.data(), static_cast<size_t>(n));
      while (static_cast<int64_t>(pend.size()) >= chunk) {
        size_t cut = split_point(pend);
        if (cut == 0) break;  // one line longer than the chunk: keep growing
        emit_sub(p, res, u, emitted++, pend.substr(0, cut), stats);
        pend.erase(0, cut);
      }
    }
    if (derr.empty() && !pend.empty() &&
        p->abort_after.load() >= static_cast<int64_t>(u))
      emit_sub(p, res, u, emitted++, std::move(pend), stats);
  }
  {
    std::lock_guard<std::mutex> lk(p->mu);
    if (!derr.empty()) res->error = derr;
    res->n_subs_final = emitted;
    res->decoder_done = true;
  }
  if (!derr.empty()) abort_at(p, u);
  p->cv.notify_all();
}

// Leader half of the pipeline: spawn the decoder, then parse subtasks (its
// own unit's or any other pipeline's) until the decoder finishes.
void run_pipeline(Parallel* p, size_t u, UnitResult* res, int thread_idx,
                  Stats* stats) {
  {
    std::lock_guard<std::mutex> lk(p->mu);
    res->pipelined = true;
    p->active_pipelines++;
  }
  p->cv.notify_all();
  std::thread dec(decoder_main, p, u, res, stats);
  while (true) {
    Subtask st;
    {
      std::unique_lock<std::mutex> lk(p->mu);
      p->cv.wait(lk, [&] { return !p->subq.empty() || res->decoder_done; });
      if (p->subq.empty()) break;  // implies decoder_done
      st = std::move(p->subq.front());
      p->subq.pop_front();
    }
    p->cv.notify_all();  // wake a decoder blocked on queue capacity
    parse_subtask(p, std::move(st), thread_idx, stats);
  }
  dec.join();
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->active_pipelines--;
  }
  p->cv.notify_all();
}

void process_unit(Parallel* p, size_t ui, int thread_idx, Stats* stats) {
  const Unit& u = p->units[ui];
  UnitResult* res = &p->results[ui];
  bool gz_unit = (u.kind == K_STREAM && u.is_gz) || u.kind == K_MEMBER;
  if (gz_unit && p->gz_pipeline && u.len > p->gz_chunk) {
    run_pipeline(p, ui, res, thread_idx, stats);
    return;
  }
  ThreadShard* sh = p->shards[thread_idx].get();
  std::string err;
  ParseCtx ctx{&sh->in, &res->triples, &err, p->swar,
               /*stable=*/u.kind == K_MMAP};
  auto handle = [&](const char* line, size_t len) -> bool {
    if (p->skip_comments && len > 0 && line[0] == '#') return true;
    int rc = parse_line(&ctx, line, len, p->tabs, p->quad);
    if (rc < 0) {
      err += std::string(" in ") + u.path;
      return false;
    }
    return true;
  };
  int64_t io_ns = 0, bytes = 0;
  auto t0 = Clock::now();
  bool ok;
  switch (u.kind) {
    case K_MMAP:
      ok = for_mapped_lines(u.map, u.map_size, u.off, u.len, p->swar, handle,
                            &bytes);
      break;
    case K_CHUNK:
      ok = for_chunk_lines(u.path.c_str(), u.off, u.len, p->swar, &err,
                           handle, &io_ns, &bytes);
      break;
    case K_MEMBER: {
      MemberSource src(u.path.c_str(), u.off, u.len);
      if (!src.ok()) {
        res->error = std::string("cannot open ") + u.path;
        return;
      }
      ok = for_stream_lines(src, u.path.c_str(), p->swar, &err, handle,
                            &io_ns, &bytes);
      break;
    }
    case K_STREAM:
    default: {
      GzSource src(u.path.c_str());
      if (!src.ok()) {
        res->error = std::string("cannot open ") + u.path;
        return;
      }
      ok = for_stream_lines(src, u.path.c_str(), p->swar, &err, handle,
                            &io_ns, &bytes);
      break;
    }
  }
  int64_t wall = ns_since(t0);
  if (gz_unit)
    stats->decode_ns += io_ns;
  else
    stats->read_ns += io_ns;
  stats->parse_ns += wall - io_ns;
  stats->bytes_read += bytes;
  if (!ok) res->error = err;
}

void worker_main(Parallel* p, int thread_idx, Stats* stats) {
  while (true) {
    size_t u = p->next_unit.fetch_add(1);
    if (u >= p->units.size()) break;
    UnitResult* res = &p->results[u];
    res->thread = thread_idx;
    if (static_cast<int64_t>(u) > p->abort_after.load()) {
      res->skipped = true;  // after a failure; never delivered
    } else {
      process_unit(p, u, thread_idx, stats);
      if (!res->error.empty()) abort_at(p, u);
    }
    {
      std::lock_guard<std::mutex> lk(p->mu);
      p->done[u] = 1;
    }
    p->cv.notify_all();
  }
  // Drain phase: units are exhausted, but live pipelines may still be
  // emitting subtasks — keep parsing until every decoder has finished and
  // the queue is empty.
  while (true) {
    Subtask st;
    {
      std::unique_lock<std::mutex> lk(p->mu);
      p->cv.wait(lk,
                 [&] { return !p->subq.empty() || p->active_pipelines == 0; });
      if (p->subq.empty()) break;  // implies no active pipelines
      st = std::move(p->subq.front());
      p->subq.pop_front();
    }
    p->cv.notify_all();
    parse_subtask(p, std::move(st), thread_idx, stats);
  }
}

// Runs fn(i) for i in [0, n) on up to `threads` std::threads (merge-stage
// parallelism; workers have already joined by the time this runs).
template <typename F>
void parallel_for(int64_t n, int threads, F&& fn) {
  if (n <= 0) return;
  int use = static_cast<int>(
      std::max<int64_t>(1, std::min<int64_t>(threads, n)));
  if (use == 1) {
    for (int64_t i = 0; i < n; i++) fn(i);
    return;
  }
  std::atomic<int64_t> next{0};
  std::vector<std::thread> pool;
  for (int t = 0; t < use; t++)
    pool.emplace_back([&] {
      int64_t i;
      while ((i = next.fetch_add(1)) < n) fn(i);
    });
  for (auto& t : pool) t.join();
}

int64_t file_size(const char* path) {
  struct stat st;
  if (stat(path, &st) != 0) return -1;
  return static_cast<int64_t>(st.st_size);
}

bool ends_with_gz(const std::string& p) {
  return p.size() >= 3 && p.compare(p.size() - 3, 3, ".gz") == 0;
}

// gzip magic sniff: gzopen transparently decompresses gzip CONTENT whatever
// the extension, so routing (mmap vs stream, member scan) must look at the
// bytes, not the name, to keep every engine's behavior identical.
bool has_gz_magic(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  unsigned char m[2];
  size_t n = fread(m, 1, 2, f);
  fclose(f);
  return n == 2 && m[0] == 0x1f && m[1] == 0x8b;
}

}  // namespace

extern "C" {

Ingest* rdf_ingest_new() { return new Ingest(); }

void rdf_ingest_free(Ingest* ing) { delete ing; }

const char* rdf_ingest_error(Ingest* ing) { return ing->error.c_str(); }

// Speed-rung knobs, resolved Python-side (RDFIND_INGEST_SWAR,
// RDFIND_INGEST_MMAP, RDFIND_INGEST_GZ_CHUNK_BYTES,
// RDFIND_INGEST_GZ_PIPELINE).  Call before any file/begin call;
// gz_chunk_bytes <= 0 keeps the default.
void rdf_ingest_set_opts(Ingest* ing, int swar, int use_mmap,
                         int64_t gz_chunk_bytes, int gz_pipeline) {
  ing->opt_swar = swar != 0;
  ing->opt_mmap = use_mmap != 0;
  ing->opt_gz_pipeline = gz_pipeline != 0;
  if (gz_chunk_bytes > 0)
    ing->opt_gz_chunk = std::max<int64_t>(gz_chunk_bytes, 256);
}

// --- Serial path (the reference implementation of the id contract) ---------

// Reads and parses one file; returns triples parsed from it, or -1 on error.
int64_t rdf_ingest_file(Ingest* ing, const char* path, int tabs,
                        int expect_quad, int skip_comments) {
  if (ing->finalized) {
    ing->error = "ingest already finalized";
    return -1;
  }
  if (ing->par) {
    ing->error = "streaming ingest already begun; use the block API";
    return -1;
  }
  int64_t count = 0;
  ParseCtx ctx{&ing->dict, &ing->triples, &ing->error, ing->opt_swar,
               /*stable=*/false};
  auto handle = [&](const char* line, size_t len) -> bool {
    if (skip_comments && len > 0 && line[0] == '#') return true;
    int rc = parse_line(&ctx, line, len, tabs != 0, expect_quad != 0);
    if (rc < 0) {
      ing->error += std::string(" in ") + path;
      return false;
    }
    count += rc;
    return true;
  };
  int64_t io_ns = 0, bytes = 0;
  int64_t size = file_size(path);
  bool gz = ends_with_gz(path) || (size >= 2 && has_gz_magic(path));
  bool ok;
  auto t0 = Clock::now();
  const char* data =
      (!gz && ing->opt_mmap && size > 0) ? ing->map_file(path, size) : nullptr;
  if (data) {
    ctx.stable = true;
    ok = for_mapped_lines(data, size, 0, size, ing->opt_swar, handle, &bytes);
    ing->stats.mmap_bytes += size;
  } else {
    GzSource src(path);
    if (!src.ok()) {
      ing->error = std::string("cannot open ") + path;
      return -1;
    }
    ok = for_stream_lines(src, path, ing->opt_swar, &ing->error, handle,
                          &io_ns, &bytes);
  }
  if (gz)
    ing->stats.decode_ns += io_ns;
  else
    ing->stats.read_ns += io_ns;
  ing->stats.parse_ns += ns_since(t0) - io_ns;
  ing->stats.bytes_read += bytes;
  ing->stats.n_files++;
  ing->stats.n_units++;
  return ok ? count : -1;
}

// Sorts the dictionary by bytes, remaps triple ids to sorted ranks.
// Returns the number of distinct values.
int64_t rdf_ingest_finalize(Ingest* ing) {
  if (!ing->finalized) {
    auto t0 = Clock::now();
    size_t nvals = ing->dict.by_id.size();
    std::vector<int32_t> order(nvals);
    std::iota(order.begin(), order.end(), 0);
    std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
      return ing->dict.by_id[a] < ing->dict.by_id[b];
    });
    ing->remap.assign(nvals, 0);
    for (size_t rank = 0; rank < nvals; rank++)
      ing->remap[order[rank]] = static_cast<int32_t>(rank);
    ing->stats.merge_ns += ns_since(t0);
    t0 = Clock::now();
    for (auto& id : ing->triples) id = ing->remap[id];
    // sorted export views + offsets.
    ing->sorted_vals.resize(nvals);
    ing->sorted_offsets.assign(nvals + 1, 0);
    int64_t off = 0;
    for (size_t rank = 0; rank < nvals; rank++) {
      std::string_view s = ing->dict.by_id[order[rank]];
      ing->sorted_vals[rank] = s;
      ing->sorted_offsets[rank] = off;
      off += static_cast<int64_t>(s.size());
    }
    ing->sorted_offsets[nvals] = off;
    ing->values_bytes = off;
    ing->stats.remap_ns += ns_since(t0);
    ing->finalized = true;
  }
  return static_cast<int64_t>(ing->sorted_vals.size());
}

int64_t rdf_ingest_num_triples(Ingest* ing) {
  return static_cast<int64_t>(ing->triples.size() / 3);
}

void rdf_ingest_get_triples(Ingest* ing, int32_t* out) {
  memcpy(out, ing->triples.data(), ing->triples.size() * sizeof(int32_t));
}

int64_t rdf_ingest_values_bytes(Ingest* ing) { return ing->values_bytes; }

// buf receives the concatenated sorted value bytes; offsets receives
// num_values + 1 prefix offsets into buf.
void rdf_ingest_get_values(Ingest* ing, char* buf, int64_t* offsets) {
  if (!ing->finalized) return;
  size_t nvals = ing->sorted_vals.size();
  for (size_t i = 0; i < nvals; i++)
    memcpy(buf + ing->sorted_offsets[i], ing->sorted_vals[i].data(),
           ing->sorted_vals[i].size());
  memcpy(offsets, ing->sorted_offsets.data(), (nvals + 1) * sizeof(int64_t));
}

// --- Parallel streaming path -----------------------------------------------

// Enqueues all files as parse units (splitting large plain files into
// chunk_bytes byte ranges at newline boundaries and multi-member gz files at
// exact member boundaries) and starts n_threads workers.  chunk_bytes <= 0
// auto-sizes the grain to input_bytes / (threads * 4), clamped to
// [1 MiB, 64 MiB], so every thread sees several units without shredding the
// input into sub-megabyte stripes.  Returns the number of units, or -1.
int64_t rdf_ingest_begin(Ingest* ing, const char** paths, int64_t n_paths,
                         int tabs, int expect_quad, int skip_comments,
                         int n_threads, int64_t chunk_bytes) {
  if (ing->par) {
    ing->error = "streaming ingest already begun";
    return -1;
  }
  if (ing->finalized || !ing->triples.empty()) {
    ing->error = "handle already used by the serial API";
    return -1;
  }
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 256) n_threads = 256;
  std::vector<int64_t> sizes(n_paths);
  int64_t total_bytes = 0;
  for (int64_t i = 0; i < n_paths; i++) {
    sizes[i] = file_size(paths[i]);
    if (sizes[i] > 0) total_bytes += sizes[i];
  }
  if (chunk_bytes <= 0) {
    chunk_bytes = total_bytes / (static_cast<int64_t>(n_threads) * 4);
    chunk_bytes = std::max<int64_t>(1ll << 20,
                                    std::min<int64_t>(chunk_bytes, 64ll << 20));
  }
  auto par = std::make_unique<Parallel>();
  par->tabs = tabs != 0;
  par->quad = expect_quad != 0;
  par->skip_comments = skip_comments != 0;
  par->swar = ing->opt_swar;
  par->gz_pipeline = ing->opt_gz_pipeline;
  par->gz_chunk = ing->opt_gz_chunk;
  par->subq_cap = static_cast<size_t>(2 * n_threads + 2);
  for (int64_t i = 0; i < n_paths; i++) {
    std::string path(paths[i]);
    int64_t size = sizes[i];
    ing->stats.n_files++;
    bool gz =
        ends_with_gz(path) || (size >= 2 && has_gz_magic(paths[i]));
    if (gz) {
      std::vector<std::pair<int64_t, int64_t>> members;
      if (ing->opt_gz_pipeline && n_threads > 1 && size > 64)
        members = scan_gz_members(paths[i], size);
      if (members.size() >= 2) {
        ing->stats.n_members += static_cast<int64_t>(members.size());
        for (auto& m : members) {
          Unit u;
          u.path = path;
          u.kind = K_MEMBER;
          u.is_gz = true;
          u.off = m.first;
          u.len = m.second;
          par->units.push_back(std::move(u));
        }
      } else {
        Unit u;
        u.path = path;
        u.kind = K_STREAM;
        u.is_gz = true;
        u.len = size;
        par->units.push_back(std::move(u));
      }
      continue;
    }
    const char* data =
        (ing->opt_mmap && size > 0) ? ing->map_file(path, size) : nullptr;
    if (data) {
      ing->stats.mmap_bytes += size;
      for (int64_t off = 0; off == 0 || off < size; off += chunk_bytes) {
        Unit u;
        u.path = path;
        u.kind = K_MMAP;
        u.map = data;
        u.map_size = size;
        u.off = off;
        u.len = std::min(chunk_bytes, size - off);
        par->units.push_back(std::move(u));
        if (chunk_bytes >= size) break;
      }
    } else if (size > chunk_bytes) {
      for (int64_t off = 0; off < size; off += chunk_bytes) {
        Unit u;
        u.path = path;
        u.kind = K_CHUNK;
        u.off = off;
        u.len = std::min(chunk_bytes, size - off);
        par->units.push_back(std::move(u));
      }
    } else {
      Unit u;  // small plain file (or unknown size): one gzopen stream unit
      u.path = path;
      u.kind = K_STREAM;
      u.len = size;
      par->units.push_back(std::move(u));
    }
  }
  par->results.resize(par->units.size());
  par->done.assign(par->units.size(), 0);
  par->shards.reserve(n_threads);
  for (int t = 0; t < n_threads; t++)
    par->shards.push_back(std::make_unique<ThreadShard>());
  ing->stats.n_threads = n_threads;
  ing->stats.n_units = static_cast<int64_t>(par->units.size());
  Parallel* p = par.get();
  ing->par = std::move(par);
  for (int t = 0; t < n_threads; t++)
    p->workers.emplace_back(worker_main, p, t, &ing->stats);
  return static_cast<int64_t>(p->units.size());
}

// Blocks until the next block (in unit order; a pipelined gz unit delivers
// one block per decoded chunk, in chunk order) is parsed; returns its row
// count (possibly 0), -1 when the stream is exhausted, -2 on parse error
// (rdf_ingest_error holds the first failing unit's message).
int64_t rdf_ingest_next_block(Ingest* ing) {
  Parallel* p = ing->par.get();
  if (!p) {
    ing->error = "rdf_ingest_begin was not called";
    return -2;
  }
  while (true) {
    if (p->next_deliver >= p->units.size()) {
      p->drained = true;
      p->join_workers();
      return -1;
    }
    size_t u = p->next_deliver;
    enum { DELIVER, ADVANCE, FAIL } outcome;
    int64_t nrows = 0;
    {
      std::unique_lock<std::mutex> lk(p->mu);
      auto ready = [&] {
        UnitResult& r = p->results[u];
        if (r.pipelined) {
          if (r.next_sub < r.subs.size() && r.subs[r.next_sub].done)
            return true;
          return r.decoder_done && r.next_sub >= r.n_subs_final;
        }
        return p->done[u] != 0;
      };
      if (!ready()) {
        ing->stats.queue_stalls++;
        auto t0 = Clock::now();
        p->cv.wait(lk, ready);
        ing->stats.stall_ns += ns_since(t0);
      }
      UnitResult& r = p->results[u];
      if (r.pipelined && r.next_sub < r.subs.size()) {
        SubBlock& sb = r.subs[r.next_sub];
        if (!sb.error.empty()) {
          ing->error = sb.error;
          outcome = FAIL;
        } else {
          p->cur_triples = &sb.triples;
          p->cur_thread = sb.thread;
          r.next_sub++;
          nrows = static_cast<int64_t>(sb.triples.size() / 3);
          outcome = DELIVER;
        }
      } else if (r.pipelined) {
        // Pipelined unit exhausted: surface a decode error, else move on.
        if (!r.error.empty()) {
          ing->error = r.error;
          outcome = FAIL;
        } else {
          p->next_deliver++;
          outcome = ADVANCE;
        }
      } else if (!r.error.empty()) {
        ing->error = r.error;
        outcome = FAIL;
      } else {
        p->cur_triples = &r.triples;
        p->cur_thread = r.thread;
        p->next_deliver++;
        nrows = static_cast<int64_t>(r.triples.size() / 3);
        outcome = DELIVER;
      }
    }
    if (outcome == FAIL) {
      p->join_workers();
      return -2;
    }
    if (outcome == DELIVER) return nrows;
    // ADVANCE: loop for the next unit.
  }
}

int rdf_ingest_block_thread(Ingest* ing) {
  Parallel* p = ing->par.get();
  if (!p || !p->cur_triples) return -1;
  return p->cur_thread;
}

// Copies the current block's (n, 3) provisional-id rows out and frees them.
void rdf_ingest_block_copy(Ingest* ing, int32_t* out) {
  Parallel* p = ing->par.get();
  if (!p || !p->cur_triples) return;
  auto& t = *p->cur_triples;
  memcpy(out, t.data(), t.size() * sizeof(int32_t));
  std::vector<int32_t>().swap(t);  // streamed blocks never linger
}

// Merges the per-thread interners into the global byte-sorted dictionary:
// crc32-shard partition -> parallel per-shard dedupe+sort -> S-way rank
// merge -> per-thread local->global tables.  Returns the number of distinct
// values, or -1 on error.  Requires the stream to be drained first.
int64_t rdf_ingest_stream_finish(Ingest* ing) {
  Parallel* p = ing->par.get();
  if (!p) {
    ing->error = "rdf_ingest_begin was not called";
    return -1;
  }
  if (!p->drained) {
    ing->error = "stream not drained; pull blocks until -1 first";
    return -1;
  }
  if (ing->finalized) return static_cast<int64_t>(ing->sorted_vals.size());
  p->join_workers();
  const int n_threads = static_cast<int>(p->shards.size());
  const int S = n_threads;  // merge shards (same partition fn as dictionary.py)

  // Partition: per-thread local ids bucketed by crc32(value) % S.
  auto t0 = Clock::now();
  parallel_for(n_threads, n_threads, [&](int64_t ti) {
    ThreadShard* sh = p->shards[ti].get();
    sh->buckets.assign(S, {});
    size_t nvals = sh->in.by_id.size();
    sh->to_global.assign(nvals, 0);
    for (size_t lid = 0; lid < nvals; lid++) {
      std::string_view s = sh->in.by_id[lid];
      uint32_t h = crc32(0L, reinterpret_cast<const Bytef*>(s.data()),
                         static_cast<uInt>(s.size()));
      sh->buckets[h % S].push_back(static_cast<int32_t>(lid));
    }
  });
  int64_t partition_ns = ns_since(t0);

  // Dedupe+sort per shard (the parallel dictionary build).  Each entry's
  // in-shard rank lands in its thread's to_global slot (upgraded to the
  // global rank below).
  struct Entry {
    std::string_view v;
    int32_t thread;
    int32_t lid;
  };
  std::vector<std::vector<std::string_view>> shard_distinct(S);
  t0 = Clock::now();
  parallel_for(S, n_threads, [&](int64_t s) {
    std::vector<Entry> entries;
    size_t total = 0;
    for (int t = 0; t < n_threads; t++)
      total += p->shards[t]->buckets[s].size();
    entries.reserve(total);
    for (int t = 0; t < n_threads; t++)
      for (int32_t lid : p->shards[t]->buckets[s])
        entries.push_back({p->shards[t]->in.by_id[lid], t, lid});
    std::sort(entries.begin(), entries.end(),
              [](const Entry& a, const Entry& b) { return a.v < b.v; });
    auto& distinct = shard_distinct[s];
    int32_t rank = -1;
    std::string_view prev;
    for (const Entry& e : entries) {
      if (rank < 0 || e.v != prev) {
        rank++;
        prev = e.v;
        distinct.push_back(e.v);
      }
      p->shards[e.thread]->to_global[e.lid] = rank;  // in-shard rank, for now
    }
  });
  ing->stats.intern_ns += ns_since(t0);

  // S-way merge of the shard-sorted runs into the byte-sorted global order
  // (shards are hash-disjoint, so no cross-shard duplicates).
  t0 = Clock::now();
  int64_t total = 0;
  for (int s = 0; s < S; s++) total += shard_distinct[s].size();
  if (total >= (1ll << 31) - 1) {
    ing->error = "dictionary exceeds int32 id space";
    return -1;
  }
  ing->sorted_vals.reserve(total);
  std::vector<std::vector<int32_t>> shard_to_global(S);
  std::vector<size_t> cursor(S, 0);
  for (int s = 0; s < S; s++)
    shard_to_global[s].resize(shard_distinct[s].size());
  for (int64_t rank = 0; rank < total; rank++) {
    int best = -1;
    for (int s = 0; s < S; s++) {
      if (cursor[s] >= shard_distinct[s].size()) continue;
      if (best < 0 ||
          shard_distinct[s][cursor[s]] < shard_distinct[best][cursor[best]])
        best = s;
    }
    shard_to_global[best][cursor[best]] = static_cast<int32_t>(rank);
    ing->sorted_vals.push_back(shard_distinct[best][cursor[best]]);
    cursor[best]++;
  }
  ing->stats.merge_ns += partition_ns + ns_since(t0);

  // Upgrade the per-thread tables from in-shard ranks to global ranks.
  t0 = Clock::now();
  parallel_for(n_threads, n_threads, [&](int64_t ti) {
    ThreadShard* sh = p->shards[ti].get();
    for (int s = 0; s < S; s++)
      for (int32_t lid : sh->buckets[s])
        sh->to_global[lid] = shard_to_global[s][sh->to_global[lid]];
    sh->buckets.clear();
  });
  ing->stats.remap_ns += ns_since(t0);

  ing->sorted_offsets.assign(total + 1, 0);
  int64_t off = 0;
  for (int64_t i = 0; i < total; i++) {
    ing->sorted_offsets[i] = off;
    off += static_cast<int64_t>(ing->sorted_vals[i].size());
  }
  ing->sorted_offsets[total] = off;
  ing->values_bytes = off;
  ing->finalized = true;
  return total;
}

int64_t rdf_ingest_thread_vocab(Ingest* ing, int thread_idx) {
  Parallel* p = ing->par.get();
  if (!p || thread_idx < 0 ||
      thread_idx >= static_cast<int>(p->shards.size()))
    return -1;
  return static_cast<int64_t>(p->shards[thread_idx]->in.by_id.size());
}

// Copies thread thread_idx's local->global id table (rdf_ingest_thread_vocab
// entries); only valid after rdf_ingest_stream_finish.
void rdf_ingest_thread_remap(Ingest* ing, int thread_idx, int32_t* out) {
  Parallel* p = ing->par.get();
  if (!p || !ing->finalized || thread_idx < 0 ||
      thread_idx >= static_cast<int>(p->shards.size()))
    return;
  auto& tg = p->shards[thread_idx]->to_global;
  memcpy(out, tg.data(), tg.size() * sizeof(int32_t));
}

// Legacy 12-lane ingest telemetry —
// [bytes_read, read_ms, parse_ms, intern_ms, merge_ms, remap_ms, n_threads,
//  n_units, queue_stalls, stall_ms, n_files, reserved].
// Worker-phase ms are SUMS across threads (divide by n_threads for wall).
void rdf_ingest_stats(Ingest* ing, double* out) {
  const Stats& s = ing->stats;
  out[0] = static_cast<double>(s.bytes_read.load());
  out[1] = s.read_ns.load() / 1e6;
  out[2] = s.parse_ns.load() / 1e6;
  out[3] = s.intern_ns / 1e6;
  out[4] = s.merge_ns / 1e6;
  out[5] = s.remap_ns / 1e6;
  out[6] = static_cast<double>(s.n_threads);
  out[7] = static_cast<double>(s.n_units);
  out[8] = static_cast<double>(s.queue_stalls.load());
  out[9] = s.stall_ns.load() / 1e6;
  out[10] = static_cast<double>(s.n_files);
  out[11] = 0.0;
}

// Extended telemetry: the 12 legacy lanes plus
// [11] decode_ms (gz read+inflate), [12] mmap_bytes, [13] n_gz_members,
// [14] n_gz_subtasks, [15] swar, [16] mmap, [17] gz_pipeline.
// Fills min(n, 18) lanes; returns the number filled.
int64_t rdf_ingest_stats2(Ingest* ing, double* out, int64_t n) {
  double full[18];
  rdf_ingest_stats(ing, full);
  const Stats& s = ing->stats;
  full[11] = s.decode_ns.load() / 1e6;
  full[12] = static_cast<double>(s.mmap_bytes);
  full[13] = static_cast<double>(s.n_members);
  full[14] = static_cast<double>(s.n_subtasks.load());
  full[15] = ing->opt_swar ? 1.0 : 0.0;
  full[16] = ing->opt_mmap ? 1.0 : 0.0;
  full[17] = ing->opt_gz_pipeline ? 1.0 : 0.0;
  int64_t fill = std::min<int64_t>(n, 18);
  for (int64_t i = 0; i < fill; i++) out[i] = full[i];
  return fill;
}

}  // extern "C"
