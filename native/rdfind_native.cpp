// Native ingest runtime: multi-file N-Triples/N-Quads reader + tokenizer +
// string interner, exposed as a C API for ctypes.
//
// Plays the role of the reference's JVM ingest infrastructure —
// MultiFileTextInputFormat (rdfind-flink/.../persistence/MultiFileTextInputFormat
// .java:49-368: many files, gz-aware, comment filtering) plus the rdf-converter
// NTriples/NQuads parsers (RDFind.scala:219-237) plus the value dictionary
// (here exact interning, see rdfind_tpu/dictionary.py) — fused into one pass so
// triple ids land directly in an int32 buffer ready for the device pipeline.
//
// Two execution modes share one handle type:
//
//   * the SERIAL path (rdf_ingest_file + rdf_ingest_finalize): one thread,
//     one interner, byte-sort + remap at the end.  This is the reference
//     implementation of the id contract below and stays deliberately simple.
//   * the PARALLEL STREAMING path (rdf_ingest_begin / rdf_ingest_next_block /
//     rdf_ingest_stream_finish): a work-stealing unit queue (whole files, or
//     newline-bounded byte ranges of large PLAIN files — gz members are not
//     seekable, so .gz splits at file granularity only, exactly like the
//     reference where gz is unsplittable, MultiFileTextInputFormat.java:
//     225-230) feeding N worker threads, each with its own arena-backed
//     interner emitting provisional thread-local ids.  Committed unit blocks
//     stream to the caller IN UNIT ORDER while later units still parse; the
//     finish step hash-partitions the per-thread interners into S shards
//     (crc32 % S — the SAME partition function as the multi-host dictionary,
//     rdfind_tpu/dictionary.py:value_shard), dedupes each shard in parallel,
//     S-way-merges the shard-sorted runs into the byte-sorted global rank
//     order, and exports per-thread local→global remap tables for the caller
//     to rewrite its streamed blocks.
//
// The id contract (BOTH paths, bit-identical by construction):
//   * terms keep surface syntax (<iri>, _:blank, "lit"@lang, "lit"^^<t>);
//   * ids are ranks in byte-sorted order of the distinct values, which equals
//     np.unique's code-point order for valid UTF-8;
//   * triples keep input order (file order, then line order; a split plain
//     file's chunks are delivered in offset order);
//   * universal newlines (\n, \r\n, \r), '#' comment lines skipped;
//   * .gz inputs transparently decompressed (zlib gzopen also passes through
//     plain files, so one read path serves both).
//
// Chunk ownership rule (Hadoop-style line splits): a chunk [o, e) with o > 0
// first discards bytes through the first line terminator at/after o, then
// parses every line whose first byte starts at position <= e (reading past e
// to finish its last line).  A line starting exactly at e belongs to the
// chunk ENDING at e; the next chunk's unconditional discard drops it.  Every
// line is therefore parsed exactly once, for any chunking.

#include <zlib.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <numeric>
#include <string>
#include <string_view>
#include <sys/stat.h>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

int64_t ns_since(Clock::time_point t0) {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(Clock::now() -
                                                              t0)
      .count();
}

// Per-phase ingest telemetry (exported via rdf_ingest_stats).  Worker-side
// counters are atomics (summed across threads); merge-stage counters are
// written single-threaded after the join.
struct Stats {
  std::atomic<int64_t> bytes_read{0};  // post-decompression bytes parsed
  std::atomic<int64_t> read_ns{0};     // time inside gzread/fread calls
  std::atomic<int64_t> parse_ns{0};    // tokenize+intern (unit wall - read)
  int64_t intern_ns = 0;               // shard dedupe+sort (dictionary build)
  int64_t merge_ns = 0;                // partition + global rank merge
  int64_t remap_ns = 0;                // local->global table construction
  std::atomic<int64_t> queue_stalls{0};  // next_block waits that blocked
  std::atomic<int64_t> stall_ns{0};      // total blocked time in next_block
  int64_t n_units = 0;
  int64_t n_files = 0;
  int n_threads = 1;
};

// Arena-backed interner: string bytes live in stable deque chunks so the
// string_view keys stay valid while the map grows.  One per handle on the
// serial path; one per worker thread on the parallel path.
struct Interner {
  std::deque<std::string> arena;
  std::unordered_map<std::string_view, int32_t> intern;
  std::vector<const std::string*> by_id;  // provisional id -> string

  int32_t intern_token(const char* s, size_t len) {
    std::string_view key(s, len);
    auto it = intern.find(key);
    if (it != intern.end()) return it->second;
    arena.emplace_back(s, len);
    int32_t id = static_cast<int32_t>(by_id.size());
    by_id.push_back(&arena.back());
    intern.emplace(std::string_view(arena.back()), id);
    return id;
  }
};

// Everything one parsed line needs: where ids come from, where triples go,
// where errors land.  Serial parsing points at the handle's members; each
// parallel worker points at its own shard + the unit's triple buffer.
struct ParseCtx {
  Interner* in;
  std::vector<int32_t>* triples;
  std::string* error;
};

struct Parallel;  // fwd

struct Ingest {
  Interner dict;                  // serial-path interner
  std::vector<int32_t> triples;   // serial path: flat (n, 3)
  std::vector<int32_t> remap;     // serial path: provisional id -> rank
  // Export representation shared by both paths after finalize/stream_finish:
  std::vector<std::string_view> sorted_vals;  // byte-sorted distinct values
  std::vector<int64_t> sorted_offsets;        // prefix offsets
  int64_t values_bytes = 0;
  std::string error;
  bool finalized = false;
  Stats stats;
  std::unique_ptr<Parallel> par;  // non-null once rdf_ingest_begin ran
};

// --- Tokenizer (mirrors ntriples._scan_term) -------------------------------

struct Term {
  const char* p;
  size_t len;
};

bool is_ws(char c) { return c == ' ' || c == '\t'; }

// Scans one term at line[i]; returns next index or (size_t)-1 on error.
size_t scan_term(const char* line, size_t i, size_t n, Term* out,
                 std::string* err) {
  char c = line[i];
  if (c == '<') {  // IRI
    const char* close =
        static_cast<const char*>(memchr(line + i + 1, '>', n - i - 1));
    if (!close) {
      *err = "unterminated IRI";
      return static_cast<size_t>(-1);
    }
    size_t j = static_cast<size_t>(close - line) + 1;
    *out = {line + i, j - i};
    return j;
  }
  if (c == '"') {  // literal with escapes, optional @lang / ^^<dtype>
    size_t j = i + 1;
    while (j < n) {
      if (line[j] == '\\') {
        j += 2;
        continue;
      }
      if (line[j] == '"') break;
      j++;
    }
    if (j >= n) {
      *err = "unterminated literal";
      return static_cast<size_t>(-1);
    }
    j++;  // past closing quote
    if (j < n && line[j] == '@') {
      while (j < n && !is_ws(line[j])) j++;
    } else if (j + 1 < n && line[j] == '^' && line[j + 1] == '^') {
      j += 2;
      if (j < n && line[j] == '<') {
        const char* close =
            static_cast<const char*>(memchr(line + j + 1, '>', n - j - 1));
        if (!close) {
          *err = "unterminated datatype IRI";
          return static_cast<size_t>(-1);
        }
        j = static_cast<size_t>(close - line) + 1;
      }
    }
    *out = {line + i, j - i};
    return j;
  }
  // blank node / bare token: read to whitespace
  size_t j = i;
  while (j < n && !is_ws(line[j])) j++;
  *out = {line + i, j - i};
  return j;
}

// Parses one line into interned (s, p, o); returns 1 on triple, 0 on blank
// line, -1 on error.
int parse_line(ParseCtx* ctx, const char* line, size_t n, bool tabs,
               bool expect_quad) {
  if (tabs) {
    // split("\t"), need >= 3 fields (parse_tab_line).
    bool blank = true;
    for (size_t k = 0; k < n; k++) {
      if (!is_ws(line[k])) {
        blank = false;
        break;
      }
    }
    if (blank) return 0;
    const char* field = line;
    const char* end = line + n;
    int32_t ids[3];
    int got = 0;
    while (got < 3) {
      const char* tab =
          static_cast<const char*>(memchr(field, '\t', end - field));
      const char* fe = tab ? tab : end;
      ids[got++] = ctx->in->intern_token(field, fe - field);
      if (!tab) break;
      field = tab + 1;
    }
    if (got < 3) {
      *ctx->error = "expected 3 tab-separated fields";
      return -1;
    }
    ctx->triples->insert(ctx->triples->end(), ids, ids + 3);
    return 1;
  }
  size_t i = 0;
  int32_t ids[3];
  int got = 0;
  int want = expect_quad ? 4 : 3;
  while (i < n && got < want) {
    while (i < n && is_ws(line[i])) i++;
    if (i >= n || line[i] == '.') break;
    Term t;
    i = scan_term(line, i, n, &t, ctx->error);
    if (i == static_cast<size_t>(-1)) return -1;
    if (got < 3) ids[got] = ctx->in->intern_token(t.p, t.len);
    got++;
  }
  if (got == 0) return 0;
  if (got < 3) {
    *ctx->error = "expected 3 terms, got " + std::to_string(got);
    return -1;
  }
  ctx->triples->insert(ctx->triples->end(), ids, ids + 3);
  return 1;
}

// --- Line streaming --------------------------------------------------------

// Streams universal-newline lines from an opened gz file (plain files pass
// through) into handle(line, len) -> bool.  Returns false on read error or
// handle failure (err set).  read_ns/bytes accumulate I/O telemetry.
template <typename H>
bool for_gz_lines(gzFile f, const char* path, std::string* err, H&& handle,
                  int64_t* read_ns, int64_t* bytes_read) {
  std::vector<char> buf(1 << 20);
  std::string carry;  // partial line across read chunks
  bool ok = true;
  while (ok) {
    auto t0 = Clock::now();
    int nread = gzread(f, buf.data(), static_cast<unsigned>(buf.size()));
    *read_ns += ns_since(t0);
    if (nread < 0) {
      int errnum = 0;
      *err = std::string("read error in ") + path + ": " + gzerror(f, &errnum);
      return false;
    }
    if (nread == 0) break;
    *bytes_read += nread;
    const char* p = buf.data();
    const char* end = p + nread;
    while (p < end) {
      const char* nl = p;
      while (nl < end && *nl != '\n' && *nl != '\r') nl++;
      if (nl == end) {  // no terminator in the rest of this chunk
        carry.append(p, end - p);
        break;
      }
      if (!carry.empty()) {
        carry.append(p, nl - p);
        ok = handle(carry.data(), carry.size());
        carry.clear();
      } else {
        ok = handle(p, nl - p);
      }
      if (!ok) break;
      // universal newlines: \r\n counts once
      p = nl + ((*nl == '\r' && nl + 1 < end && nl[1] == '\n') ? 2 : 1);
      // NB: a \r\n split exactly across chunks yields one empty extra line,
      // which parses as blank — harmless.
    }
  }
  if (ok && !carry.empty()) ok = handle(carry.data(), carry.size());
  return ok;
}

// Streams the lines OWNED by byte range [off, off+len) of a plain file (see
// the chunk ownership rule in the header comment) into handle().
template <typename H>
bool for_chunk_lines(const char* path, int64_t off, int64_t len,
                     std::string* err, H&& handle, int64_t* read_ns,
                     int64_t* bytes_read) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    *err = std::string("cannot open ") + path;
    return false;
  }
  if (off > 0 && fseek(f, static_cast<long>(off), SEEK_SET) != 0) {
    *err = std::string("cannot seek in ") + path;
    fclose(f);
    return false;
  }
  const int64_t end = off + len;  // lines starting at pos <= end are ours
  std::vector<char> buf(1 << 20);
  std::string carry;
  bool discard = off > 0;  // drop through the first terminator (prev owns it)
  bool pending_cr = false;  // '\r' consumed at buffer end; eat a leading '\n'
  int64_t pos = off;        // absolute offset of the next unread byte
  int64_t line_start = off;
  bool ok = true;
  bool done = false;
  while (ok && !done) {
    // Read the owned range in full-buffer strides, then finish the final
    // line in small tail reads — the overshoot past `end` stays bounded by
    // one tail stride instead of a whole buffer.
    size_t want = buf.size();
    if (pos <= end)
      want = static_cast<size_t>(
          std::min<int64_t>(static_cast<int64_t>(want), end - pos + 1));
    else
      want = 4096;
    auto t0 = Clock::now();
    size_t nread = fread(buf.data(), 1, want, f);
    *read_ns += ns_since(t0);
    if (nread == 0) break;  // EOF (or error: tail handled below)
    *bytes_read += static_cast<int64_t>(nread);
    const char* p = buf.data();
    const char* bend = p + nread;
    if (pending_cr) {
      pending_cr = false;
      if (*p == '\n') {
        p++;
        pos++;
        line_start = pos;
        if (line_start > end) {
          done = true;
          break;
        }
      }
    }
    while (p < bend) {
      const char* nl = p;
      while (nl < bend && *nl != '\n' && *nl != '\r') nl++;
      if (nl == bend) {
        if (!discard) carry.append(p, bend - p);
        pos += bend - p;
        break;
      }
      if (discard) {
        discard = false;
      } else if (!carry.empty()) {
        carry.append(p, nl - p);
        ok = handle(carry.data(), carry.size());
        carry.clear();
      } else {
        ok = handle(p, nl - p);
      }
      if (!ok) break;
      int64_t term = 1;
      if (*nl == '\r') {
        if (nl + 1 < bend) {
          if (nl[1] == '\n') term = 2;
        } else {
          pending_cr = true;  // resolve against the next refill
        }
      }
      pos += (nl - p) + term;
      p = nl + term;
      line_start = pos;
      if (line_start > end) {
        done = true;
        break;
      }
    }
  }
  if (ok && !done && !discard && !carry.empty() && line_start <= end)
    ok = handle(carry.data(), carry.size());  // final unterminated line
  fclose(f);
  return ok;
}

// --- Parallel streaming engine ---------------------------------------------

struct Unit {
  std::string path;
  int64_t off = 0;    // byte range (plain-file chunks); whole=-range unused
  int64_t len = 0;
  bool whole = true;  // read via gzopen (gz files and unsplit plain files)
};

struct UnitResult {
  std::vector<int32_t> triples;  // provisional thread-local ids
  int thread = -1;
  std::string error;
  bool skipped = false;  // queued after a failed unit; never delivered
};

struct ThreadShard {
  Interner in;
  std::vector<int32_t> to_global;  // local id -> byte-sorted global rank
  // Per-merge-shard local-id buckets (filled by the partition stage, read by
  // the dedupe and remap stages).
  std::vector<std::vector<int32_t>> buckets;
};

struct Parallel {
  std::vector<Unit> units;
  std::vector<UnitResult> results;
  std::vector<std::unique_ptr<ThreadShard>> shards;  // one per worker thread
  std::vector<std::thread> workers;
  std::atomic<size_t> next_unit{0};
  // First failed unit index: workers skip units queued after it (best-effort
  // cancellation; earlier units still complete so in-order delivery reaches
  // the failure deterministically — the same "first error wins" surface as
  // the serial path).
  std::atomic<int64_t> abort_after{INT64_MAX};
  std::mutex mu;
  std::condition_variable cv;
  std::vector<char> done;  // guarded by mu
  size_t next_deliver = 0;
  int64_t cur_block = -1;
  bool tabs = false, quad = false, skip_comments = true;
  bool joined = false;
  bool drained = false;

  void join_workers() {
    if (joined) return;
    for (auto& t : workers)
      if (t.joinable()) t.join();
    joined = true;
  }
  ~Parallel() { join_workers(); }
};

void process_unit(const Unit& u, UnitResult* res, ThreadShard* sh,
                  const Parallel& p, Stats* stats) {
  std::string err;
  ParseCtx ctx{&sh->in, &res->triples, &err};
  auto handle = [&](const char* line, size_t len) -> bool {
    if (p.skip_comments && len > 0 && line[0] == '#') return true;
    int rc = parse_line(&ctx, line, len, p.tabs, p.quad);
    if (rc < 0) {
      err += std::string(" in ") + u.path;
      return false;
    }
    return true;
  };
  int64_t read_ns = 0, bytes = 0;
  auto t0 = Clock::now();
  bool ok;
  if (u.whole) {
    gzFile f = gzopen(u.path.c_str(), "rb");
    if (!f) {
      res->error = std::string("cannot open ") + u.path;
      return;
    }
    gzbuffer(f, 1 << 20);
    ok = for_gz_lines(f, u.path.c_str(), &err, handle, &read_ns, &bytes);
    gzclose(f);
  } else {
    ok = for_chunk_lines(u.path.c_str(), u.off, u.len, &err, handle, &read_ns,
                         &bytes);
  }
  int64_t wall = ns_since(t0);
  stats->read_ns += read_ns;
  stats->parse_ns += wall - read_ns;
  stats->bytes_read += bytes;
  if (!ok) res->error = err;
}

void worker_main(Parallel* p, int thread_idx, Stats* stats) {
  ThreadShard* sh = p->shards[thread_idx].get();
  while (true) {
    size_t u = p->next_unit.fetch_add(1);
    if (u >= p->units.size()) break;
    UnitResult* res = &p->results[u];
    res->thread = thread_idx;
    if (static_cast<int64_t>(u) > p->abort_after.load()) {
      res->skipped = true;  // after a failure; never delivered
    } else {
      process_unit(p->units[u], res, sh, *p, stats);
      if (!res->error.empty()) {
        int64_t cur = p->abort_after.load();
        while (static_cast<int64_t>(u) < cur &&
               !p->abort_after.compare_exchange_weak(cur,
                                                     static_cast<int64_t>(u)))
          ;
      }
    }
    {
      std::lock_guard<std::mutex> lk(p->mu);
      p->done[u] = 1;
    }
    p->cv.notify_all();
  }
}

// Runs fn(i) for i in [0, n) on up to `threads` std::threads (merge-stage
// parallelism; workers have already joined by the time this runs).
template <typename F>
void parallel_for(int64_t n, int threads, F&& fn) {
  if (n <= 0) return;
  int use = static_cast<int>(
      std::max<int64_t>(1, std::min<int64_t>(threads, n)));
  if (use == 1) {
    for (int64_t i = 0; i < n; i++) fn(i);
    return;
  }
  std::atomic<int64_t> next{0};
  std::vector<std::thread> pool;
  for (int t = 0; t < use; t++)
    pool.emplace_back([&] {
      int64_t i;
      while ((i = next.fetch_add(1)) < n) fn(i);
    });
  for (auto& t : pool) t.join();
}

int64_t file_size(const char* path) {
  struct stat st;
  if (stat(path, &st) != 0) return -1;
  return static_cast<int64_t>(st.st_size);
}

bool ends_with_gz(const std::string& p) {
  return p.size() >= 3 && p.compare(p.size() - 3, 3, ".gz") == 0;
}

}  // namespace

extern "C" {

Ingest* rdf_ingest_new() { return new Ingest(); }

void rdf_ingest_free(Ingest* ing) { delete ing; }

const char* rdf_ingest_error(Ingest* ing) { return ing->error.c_str(); }

// --- Serial path (the reference implementation of the id contract) ---------

// Reads and parses one file; returns triples parsed from it, or -1 on error.
int64_t rdf_ingest_file(Ingest* ing, const char* path, int tabs,
                        int expect_quad, int skip_comments) {
  if (ing->finalized) {
    ing->error = "ingest already finalized";
    return -1;
  }
  if (ing->par) {
    ing->error = "streaming ingest already begun; use the block API";
    return -1;
  }
  gzFile f = gzopen(path, "rb");
  if (!f) {
    ing->error = std::string("cannot open ") + path;
    return -1;
  }
  gzbuffer(f, 1 << 20);
  int64_t count = 0;
  ParseCtx ctx{&ing->dict, &ing->triples, &ing->error};
  auto handle = [&](const char* line, size_t len) -> bool {
    if (skip_comments && len > 0 && line[0] == '#') return true;
    int rc = parse_line(&ctx, line, len, tabs != 0, expect_quad != 0);
    if (rc < 0) {
      ing->error += std::string(" in ") + path;
      return false;
    }
    count += rc;
    return true;
  };
  int64_t read_ns = 0, bytes = 0;
  auto t0 = Clock::now();
  bool ok = for_gz_lines(f, path, &ing->error, handle, &read_ns, &bytes);
  gzclose(f);
  ing->stats.read_ns += read_ns;
  ing->stats.parse_ns += ns_since(t0) - read_ns;
  ing->stats.bytes_read += bytes;
  ing->stats.n_files++;
  ing->stats.n_units++;
  return ok ? count : -1;
}

// Sorts the dictionary by bytes, remaps triple ids to sorted ranks.
// Returns the number of distinct values.
int64_t rdf_ingest_finalize(Ingest* ing) {
  if (!ing->finalized) {
    auto t0 = Clock::now();
    size_t nvals = ing->dict.by_id.size();
    std::vector<int32_t> order(nvals);
    std::iota(order.begin(), order.end(), 0);
    std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
      return *ing->dict.by_id[a] < *ing->dict.by_id[b];
    });
    ing->remap.assign(nvals, 0);
    for (size_t rank = 0; rank < nvals; rank++)
      ing->remap[order[rank]] = static_cast<int32_t>(rank);
    ing->stats.merge_ns += ns_since(t0);
    t0 = Clock::now();
    for (auto& id : ing->triples) id = ing->remap[id];
    // sorted export views + offsets.
    ing->sorted_vals.resize(nvals);
    ing->sorted_offsets.assign(nvals + 1, 0);
    int64_t off = 0;
    for (size_t rank = 0; rank < nvals; rank++) {
      const std::string* s = ing->dict.by_id[order[rank]];
      ing->sorted_vals[rank] = std::string_view(*s);
      ing->sorted_offsets[rank] = off;
      off += static_cast<int64_t>(s->size());
    }
    ing->sorted_offsets[nvals] = off;
    ing->values_bytes = off;
    ing->stats.remap_ns += ns_since(t0);
    ing->finalized = true;
  }
  return static_cast<int64_t>(ing->sorted_vals.size());
}

int64_t rdf_ingest_num_triples(Ingest* ing) {
  return static_cast<int64_t>(ing->triples.size() / 3);
}

void rdf_ingest_get_triples(Ingest* ing, int32_t* out) {
  memcpy(out, ing->triples.data(), ing->triples.size() * sizeof(int32_t));
}

int64_t rdf_ingest_values_bytes(Ingest* ing) { return ing->values_bytes; }

// buf receives the concatenated sorted value bytes; offsets receives
// num_values + 1 prefix offsets into buf.
void rdf_ingest_get_values(Ingest* ing, char* buf, int64_t* offsets) {
  if (!ing->finalized) return;
  size_t nvals = ing->sorted_vals.size();
  for (size_t i = 0; i < nvals; i++)
    memcpy(buf + ing->sorted_offsets[i], ing->sorted_vals[i].data(),
           ing->sorted_vals[i].size());
  memcpy(offsets, ing->sorted_offsets.data(), (nvals + 1) * sizeof(int64_t));
}

// --- Parallel streaming path -----------------------------------------------

// Enqueues all files as parse units (splitting large plain files into
// chunk_bytes byte ranges at newline boundaries) and starts n_threads
// workers.  Returns the number of units, or -1 on error.
int64_t rdf_ingest_begin(Ingest* ing, const char** paths, int64_t n_paths,
                         int tabs, int expect_quad, int skip_comments,
                         int n_threads, int64_t chunk_bytes) {
  if (ing->par) {
    ing->error = "streaming ingest already begun";
    return -1;
  }
  if (ing->finalized || !ing->triples.empty()) {
    ing->error = "handle already used by the serial API";
    return -1;
  }
  if (chunk_bytes <= 0) chunk_bytes = 64ll << 20;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 256) n_threads = 256;
  auto par = std::make_unique<Parallel>();
  par->tabs = tabs != 0;
  par->quad = expect_quad != 0;
  par->skip_comments = skip_comments != 0;
  for (int64_t i = 0; i < n_paths; i++) {
    std::string path(paths[i]);
    int64_t size = file_size(paths[i]);
    ing->stats.n_files++;
    if (!ends_with_gz(path) && size > chunk_bytes) {
      for (int64_t off = 0; off < size; off += chunk_bytes) {
        Unit u;
        u.path = path;
        u.whole = false;
        u.off = off;
        u.len = std::min(chunk_bytes, size - off);
        par->units.push_back(std::move(u));
      }
    } else {
      Unit u;  // gz (unsplittable) or small plain file: one whole-file unit
      u.path = path;
      par->units.push_back(std::move(u));
    }
  }
  par->results.resize(par->units.size());
  par->done.assign(par->units.size(), 0);
  par->shards.reserve(n_threads);
  for (int t = 0; t < n_threads; t++)
    par->shards.push_back(std::make_unique<ThreadShard>());
  ing->stats.n_threads = n_threads;
  ing->stats.n_units = static_cast<int64_t>(par->units.size());
  Parallel* p = par.get();
  ing->par = std::move(par);
  for (int t = 0; t < n_threads; t++)
    p->workers.emplace_back(worker_main, p, t, &ing->stats);
  return static_cast<int64_t>(p->units.size());
}

// Blocks until the next unit (in unit order) is parsed; returns its row
// count (possibly 0), -1 when the stream is exhausted, -2 on parse error
// (rdf_ingest_error holds the first failing unit's message).
int64_t rdf_ingest_next_block(Ingest* ing) {
  Parallel* p = ing->par.get();
  if (!p) {
    ing->error = "rdf_ingest_begin was not called";
    return -2;
  }
  if (p->next_deliver >= p->units.size()) {
    p->drained = true;
    p->join_workers();
    return -1;
  }
  size_t u = p->next_deliver;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    if (!p->done[u]) {
      ing->stats.queue_stalls++;
      auto t0 = Clock::now();
      p->cv.wait(lk, [&] { return p->done[u] != 0; });
      ing->stats.stall_ns += ns_since(t0);
    }
  }
  UnitResult& r = p->results[u];
  if (!r.error.empty()) {
    ing->error = r.error;
    p->join_workers();
    return -2;
  }
  p->cur_block = static_cast<int64_t>(u);
  p->next_deliver++;
  return static_cast<int64_t>(r.triples.size() / 3);
}

int rdf_ingest_block_thread(Ingest* ing) {
  Parallel* p = ing->par.get();
  if (!p || p->cur_block < 0) return -1;
  return p->results[p->cur_block].thread;
}

// Copies the current block's (n, 3) provisional-id rows out and frees them.
void rdf_ingest_block_copy(Ingest* ing, int32_t* out) {
  Parallel* p = ing->par.get();
  if (!p || p->cur_block < 0) return;
  auto& t = p->results[p->cur_block].triples;
  memcpy(out, t.data(), t.size() * sizeof(int32_t));
  std::vector<int32_t>().swap(t);  // streamed blocks never linger
}

// Merges the per-thread interners into the global byte-sorted dictionary:
// crc32-shard partition -> parallel per-shard dedupe+sort -> S-way rank
// merge -> per-thread local->global tables.  Returns the number of distinct
// values, or -1 on error.  Requires the stream to be drained first.
int64_t rdf_ingest_stream_finish(Ingest* ing) {
  Parallel* p = ing->par.get();
  if (!p) {
    ing->error = "rdf_ingest_begin was not called";
    return -1;
  }
  if (!p->drained) {
    ing->error = "stream not drained; pull blocks until -1 first";
    return -1;
  }
  if (ing->finalized) return static_cast<int64_t>(ing->sorted_vals.size());
  p->join_workers();
  const int n_threads = static_cast<int>(p->shards.size());
  const int S = n_threads;  // merge shards (same partition fn as dictionary.py)

  // Partition: per-thread local ids bucketed by crc32(value) % S.
  auto t0 = Clock::now();
  parallel_for(n_threads, n_threads, [&](int64_t ti) {
    ThreadShard* sh = p->shards[ti].get();
    sh->buckets.assign(S, {});
    size_t nvals = sh->in.by_id.size();
    sh->to_global.assign(nvals, 0);
    for (size_t lid = 0; lid < nvals; lid++) {
      const std::string* s = sh->in.by_id[lid];
      uint32_t h = crc32(0L, reinterpret_cast<const Bytef*>(s->data()),
                         static_cast<uInt>(s->size()));
      sh->buckets[h % S].push_back(static_cast<int32_t>(lid));
    }
  });
  int64_t partition_ns = ns_since(t0);

  // Dedupe+sort per shard (the parallel dictionary build).  Each entry's
  // in-shard rank lands in its thread's to_global slot (upgraded to the
  // global rank below).
  struct Entry {
    std::string_view v;
    int32_t thread;
    int32_t lid;
  };
  std::vector<std::vector<std::string_view>> shard_distinct(S);
  t0 = Clock::now();
  parallel_for(S, n_threads, [&](int64_t s) {
    std::vector<Entry> entries;
    size_t total = 0;
    for (int t = 0; t < n_threads; t++)
      total += p->shards[t]->buckets[s].size();
    entries.reserve(total);
    for (int t = 0; t < n_threads; t++)
      for (int32_t lid : p->shards[t]->buckets[s])
        entries.push_back(
            {std::string_view(*p->shards[t]->in.by_id[lid]), t, lid});
    std::sort(entries.begin(), entries.end(),
              [](const Entry& a, const Entry& b) { return a.v < b.v; });
    auto& distinct = shard_distinct[s];
    int32_t rank = -1;
    std::string_view prev;
    for (const Entry& e : entries) {
      if (rank < 0 || e.v != prev) {
        rank++;
        prev = e.v;
        distinct.push_back(e.v);
      }
      p->shards[e.thread]->to_global[e.lid] = rank;  // in-shard rank, for now
    }
  });
  ing->stats.intern_ns += ns_since(t0);

  // S-way merge of the shard-sorted runs into the byte-sorted global order
  // (shards are hash-disjoint, so no cross-shard duplicates).
  t0 = Clock::now();
  int64_t total = 0;
  for (int s = 0; s < S; s++) total += shard_distinct[s].size();
  if (total >= (1ll << 31) - 1) {
    ing->error = "dictionary exceeds int32 id space";
    return -1;
  }
  ing->sorted_vals.reserve(total);
  std::vector<std::vector<int32_t>> shard_to_global(S);
  std::vector<size_t> cursor(S, 0);
  for (int s = 0; s < S; s++)
    shard_to_global[s].resize(shard_distinct[s].size());
  for (int64_t rank = 0; rank < total; rank++) {
    int best = -1;
    for (int s = 0; s < S; s++) {
      if (cursor[s] >= shard_distinct[s].size()) continue;
      if (best < 0 ||
          shard_distinct[s][cursor[s]] < shard_distinct[best][cursor[best]])
        best = s;
    }
    shard_to_global[best][cursor[best]] = static_cast<int32_t>(rank);
    ing->sorted_vals.push_back(shard_distinct[best][cursor[best]]);
    cursor[best]++;
  }
  ing->stats.merge_ns += partition_ns + ns_since(t0);

  // Upgrade the per-thread tables from in-shard ranks to global ranks.
  t0 = Clock::now();
  parallel_for(n_threads, n_threads, [&](int64_t ti) {
    ThreadShard* sh = p->shards[ti].get();
    for (int s = 0; s < S; s++)
      for (int32_t lid : sh->buckets[s])
        sh->to_global[lid] = shard_to_global[s][sh->to_global[lid]];
    sh->buckets.clear();
  });
  ing->stats.remap_ns += ns_since(t0);

  ing->sorted_offsets.assign(total + 1, 0);
  int64_t off = 0;
  for (int64_t i = 0; i < total; i++) {
    ing->sorted_offsets[i] = off;
    off += static_cast<int64_t>(ing->sorted_vals[i].size());
  }
  ing->sorted_offsets[total] = off;
  ing->values_bytes = off;
  ing->finalized = true;
  return total;
}

int64_t rdf_ingest_thread_vocab(Ingest* ing, int thread_idx) {
  Parallel* p = ing->par.get();
  if (!p || thread_idx < 0 ||
      thread_idx >= static_cast<int>(p->shards.size()))
    return -1;
  return static_cast<int64_t>(p->shards[thread_idx]->in.by_id.size());
}

// Copies thread thread_idx's local->global id table (rdf_ingest_thread_vocab
// entries); only valid after rdf_ingest_stream_finish.
void rdf_ingest_thread_remap(Ingest* ing, int thread_idx, int32_t* out) {
  Parallel* p = ing->par.get();
  if (!p || !ing->finalized || thread_idx < 0 ||
      thread_idx >= static_cast<int>(p->shards.size()))
    return;
  auto& tg = p->shards[thread_idx]->to_global;
  memcpy(out, tg.data(), tg.size() * sizeof(int32_t));
}

// Ingest telemetry: 12 doubles —
// [bytes_read, read_ms, parse_ms, intern_ms, merge_ms, remap_ms, n_threads,
//  n_units, queue_stalls, stall_ms, n_files, reserved].
// Worker-phase ms are SUMS across threads (divide by n_threads for wall).
void rdf_ingest_stats(Ingest* ing, double* out) {
  const Stats& s = ing->stats;
  out[0] = static_cast<double>(s.bytes_read.load());
  out[1] = s.read_ns.load() / 1e6;
  out[2] = s.parse_ns.load() / 1e6;
  out[3] = s.intern_ns / 1e6;
  out[4] = s.merge_ns / 1e6;
  out[5] = s.remap_ns / 1e6;
  out[6] = static_cast<double>(s.n_threads);
  out[7] = static_cast<double>(s.n_units);
  out[8] = static_cast<double>(s.queue_stalls.load());
  out[9] = s.stall_ns.load() / 1e6;
  out[10] = static_cast<double>(s.n_files);
  out[11] = 0.0;
}

}  // extern "C"
