"""Serving-observability parity gate: the admin plane, probed live.

scripts/verify.sh runs this after the serve parity gate
(VERIFY_SKIP_SERVE_OBS=1 opts out).  It plants a tiny index, launches the
real serving process (programs/serve) with an ephemeral console port, and
checks the ISSUE-20 observability contract end to end:

  1. telemetry parity — a known mix of good and malformed queries is
     fired at the live server; /metrics must parse as Prometheus text,
     the request counters must equal the fired counts exactly (by
     endpoint x outcome, malformed traffic included — the satellite
     bugfix), and the latency histogram _count must equal the ok count;
  2. planted slow path — the server runs with RDFIND_SLO_P99_US=1 (every
     real query exceeds 1us), so /slo, /status, the heartbeat, and
     ``tpu_watch --status --json`` must all name the burning SLO ("p99");
     SIGTERM must dump the slow-query ring into --obs;
  3. planted stale bundle — a chain-broken generation 1 with an old
     commit stamp is committed under a server holding generation 0 with
     RDFIND_SLO_STALENESS_S=5; the refused swap must surface as the
     "staleness" SLO burning AND the SERVING-STALE verdict, on /slo and
     in ``tpu_watch --status --json``;
  4. obs on/off parity — the same query set against a server with
     RDFIND_SERVE_OBS=0 must return byte-identical response bodies.

A loopback bind failure is a graceful SKIP (exit 0), not a failure — the
console is best-effort by design.  Exit codes: 0 ok/skip, 1 failure.
"""

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

URL_RE = re.compile(r"console on (http://[0-9.]+:\d+)")
# Prometheus text exposition: comments/blank lines, or `name{labels} value`.
SAMPLE_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$")

N_ANS = 25   # the fixed answer set compared byte-for-byte obs on/off
N_OK = 40    # extra well-formed queries
N_BAD = 7    # malformed queries (must count as outcome="400")


def fetch(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


class Server:
    """One live serving process; parses the console URL off stderr."""

    def __init__(self, index_dir: str, obs_dir: str | None = None,
                 env_extra: dict | None = None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.update(env_extra or {})
        cmd = [sys.executable, "-m", "rdfind_tpu.programs.serve",
               index_dir, "--console-port", "0", "--max-s", "60",
               "--poll-s", "0.1"]
        if obs_dir:
            cmd += ["--obs", obs_dir]
        self.child = subprocess.Popen(cmd, cwd=REPO, env=env,
                                      stdout=subprocess.DEVNULL,
                                      stderr=subprocess.PIPE, text=True)
        self.base = None
        self.bind_failed = False
        deadline = time.time() + 60
        for line in self.child.stderr:
            if "console bind failed" in line:
                self.bind_failed = True
                break
            m = URL_RE.search(line)
            if m:
                self.base = m.group(1)
                break
            if time.time() > deadline:
                break

    def stop(self, sig=signal.SIGTERM) -> int:
        try:
            self.child.send_signal(sig)
            return self.child.wait(timeout=30)
        finally:
            self.child.stderr.close()

    def kill(self) -> None:
        self.child.kill()
        self.child.stderr.close()


def _prom_value(text: str, name: str, labels: str | None = None):
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if labels is not None:
            if not rest.startswith("{"):
                continue
            got = rest[1:rest.index("}")]
            if set(got.split(",")) != set(labels.split(",")):
                continue
            rest = rest[rest.index("}") + 1:]
        elif rest.startswith("{"):
            continue
        try:
            return float(rest.strip().split()[0])
        except (ValueError, IndexError):
            return None
    return None


def _watch_json(obs_dir: str):
    out = subprocess.run(
        [sys.executable, "tpu_watch.py", "--status", obs_dir, "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    return out.returncode, json.loads(out.stdout)


def main() -> int:
    from bench_serve import _planted
    from rdfind_tpu.runtime import serving

    failures = []
    values, table = _planted(400, seed=7)
    hold_q = "dep=0&ref=0"
    ans_urls = [f"/query/holds?dep={i % 50}&ref={i % 37}"
                for i in range(N_ANS)]

    with tempfile.TemporaryDirectory(prefix="serve_obs_") as root:
        idx_a = os.path.join(root, "idx_a")
        idx_stale = os.path.join(root, "idx_stale")
        obs_a = os.path.join(root, "obs_a")
        obs_stale = os.path.join(root, "obs_stale")
        serving.write_index(idx_a, values, table, generation=0,
                            output_digest="obs-g0")
        serving.write_index(idx_stale, values, table, generation=0,
                            output_digest="stale-g0")

        # --- 1+2: telemetry parity + planted slow path (p99 SLO) -----------
        srv = Server(idx_a, obs_dir=obs_a,
                     env_extra={"RDFIND_SLO_P99_US": "1"})
        if srv.bind_failed:
            srv.kill()
            print("serve_obs_parity: SKIP (console could not bind a "
                  "loopback port in this environment)")
            return 0
        if srv.base is None:
            srv.kill()
            print("serve_obs_parity: FAIL — server never announced a "
                  "console URL", file=sys.stderr)
            return 1
        try:
            answers_on = [fetch(srv.base + u) for u in ans_urls]
            for _ in range(N_OK):
                fetch(f"{srv.base}/query/holds?{hold_q}")
            for _ in range(N_BAD):
                try:
                    fetch(f"{srv.base}/query/holds?dep=bogus&ref=0")
                    failures.append("malformed query did not return 400")
                except urllib.error.HTTPError as e:
                    if e.code != 400:
                        failures.append(
                            f"malformed query returned {e.code} != 400")

            prom = fetch(srv.base + "/metrics").decode()
            bad = [ln for ln in prom.splitlines()
                   if ln and not ln.startswith("#")
                   and not SAMPLE_RE.match(ln)]
            if bad:
                failures.append(f"/metrics lines do not parse as "
                                f"Prometheus text: {bad[:3]}")
            n_ok = N_ANS + N_OK
            got_ok = _prom_value(prom, "rdfind_serve_requests_total",
                                 'endpoint="holds",outcome="ok"')
            got_400 = _prom_value(prom, "rdfind_serve_requests_total",
                                  'endpoint="holds",outcome="400"')
            got_cnt = _prom_value(prom,
                                  "rdfind_serve_holds_latency_us_count")
            if got_ok != n_ok:
                failures.append(f"requests_total ok={got_ok} != {n_ok} "
                                f"fired (counters lost requests)")
            if got_400 != N_BAD:
                failures.append(f"requests_total 400={got_400} != {N_BAD} "
                                f"malformed fired (satellite bugfix broke)")
            if got_cnt != n_ok:
                failures.append(f"histogram _count={got_cnt} != {n_ok} "
                                f"ok requests (torn/lossy aggregation)")

            slo = json.loads(fetch(srv.base + "/slo"))
            v = slo.get("verdict") or {}
            if v.get("state") != "burning" or v.get("slo") != "p99":
                failures.append(f"planted slow path: /slo verdict "
                                f"{v.get('state')}/{v.get('slo')} != "
                                f"burning/p99")
            st = json.loads(fetch(srv.base + "/status"))
            if (st.get("slo") or {}).get("state") != "burning":
                failures.append(f"/status slo {st.get('slo')} not burning")
            slowlog = json.loads(fetch(srv.base + "/debug/slowlog"))
            if "entries" not in slowlog:
                failures.append(f"/debug/slowlog malformed: {slowlog}")

            time.sleep(1.0)  # let a beat carry the burning verdict
            rc, watch = _watch_json(obs_a)
            if rc != 0:
                failures.append(f"tpu_watch --status exit {rc} != 0 "
                                f"(exit codes must be unchanged)")
            if not watch.get("slo_burning"):
                failures.append(f"tpu_watch --json slo_burning="
                                f"{watch.get('slo_burning')} != true")
        finally:
            rc = srv.stop()
        if rc not in (0, 128 + signal.SIGTERM):
            failures.append(f"server A exit code {rc}")
        dump = os.path.join(obs_a, "slowlog-host0.json")
        if not os.path.exists(dump):
            failures.append("SIGTERM did not dump the slow-query ring "
                            f"({dump} missing)")

        # --- 3: planted stale bundle (staleness SLO + SERVING-STALE) -------
        srv = Server(idx_stale, obs_dir=obs_stale,
                     env_extra={"RDFIND_SLO_STALENESS_S": "5"})
        if srv.base is None:
            srv.kill()
            print("serve_obs_parity: FAIL — stale-gate server never "
                  "announced a console URL", file=sys.stderr)
            return 1
        try:
            # A chain-broken generation 1 whose data committed 60s ago:
            # the swap must be refused and staleness must burn.
            serving.write_index(
                idx_stale, values, table, generation=1,
                output_digest="stale-g1",
                base_output_digest="not-the-served-digest",
                extra={"bundle_commit_unix": round(time.time() - 60, 3)})
            deadline = time.time() + 20
            v = {}
            while time.time() < deadline:
                time.sleep(0.5)
                slo = json.loads(fetch(srv.base + "/slo"))
                v = slo.get("verdict") or {}
                if v.get("state") == "burning":
                    break
            if v.get("state") != "burning" or v.get("slo") != "staleness":
                failures.append(f"planted stale bundle: /slo verdict "
                                f"{v.get('state')}/{v.get('slo')} != "
                                f"burning/staleness")
            fresh = slo.get("freshness") or {}
            if fresh.get("generations_behind") != 1:
                failures.append(f"freshness generations_behind="
                                f"{fresh.get('generations_behind')} != 1")
            time.sleep(1.0)
            rc, watch = _watch_json(obs_stale)
            if rc != 0:
                failures.append(f"tpu_watch --status (stale) exit {rc}")
            if not watch.get("slo_burning") or not watch.get(
                    "serving_stale"):
                failures.append(
                    f"tpu_watch --json slo_burning="
                    f"{watch.get('slo_burning')} serving_stale="
                    f"{watch.get('serving_stale')} — both must be true")
        finally:
            rc = srv.stop()

        # --- 4: obs off — byte-identical answers ---------------------------
        srv = Server(idx_a, env_extra={"RDFIND_SERVE_OBS": "0"})
        if srv.base is None:
            srv.kill()
            print("serve_obs_parity: FAIL — obs-off server never "
                  "announced a console URL", file=sys.stderr)
            return 1
        try:
            answers_off = [fetch(srv.base + u) for u in ans_urls]
            prom_off = fetch(srv.base + "/metrics").decode()
        finally:
            srv.stop()
        if answers_on != answers_off:
            diff = sum(a != b for a, b in zip(answers_on, answers_off))
            failures.append(f"obs on/off answers differ on {diff}/"
                            f"{N_ANS} queries (must be byte-identical)")
        if _prom_value(prom_off, "rdfind_serve_requests_total",
                       'endpoint="holds",outcome="ok"') not in (None, 0.0):
            failures.append("RDFIND_SERVE_OBS=0 still counted requests")

    if failures:
        for f in failures:
            print(f"serve_obs_parity: {f}", file=sys.stderr)
        return 1
    print("serve_obs_parity: OK — live /metrics parses with exact "
          "request/histogram counts (malformed traffic counted), planted "
          "slow path burns the p99 SLO and planted stale bundle burns the "
          "staleness SLO on /slo + heartbeat + tpu_watch --status, "
          "SIGTERM dumps the slowlog, and answers are byte-identical "
          "with observability off")
    return 0


if __name__ == "__main__":
    sys.exit(main())
