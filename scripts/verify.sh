#!/usr/bin/env bash
# One-command verification gate: the ROADMAP tier-1 test recipe followed by
# a tiny CPU bench whose row feeds the perf-regression sentinel
# (python -m rdfind_tpu.obs.sentinel --check).
#
# Usage:
#   scripts/verify.sh                  # tests + tiny bench + sentinel gate
#   VERIFY_SKIP_BENCH=1 scripts/verify.sh   # tests only (fast pre-commit)
#   BENCH_HISTORY=/path/h.jsonl scripts/verify.sh   # custom history file
#
# Exit codes: the tier-1 suite's rc when tests fail; 1 when the tiny bench
# dies or the sentinel flags a regression; 0 otherwise.  The sentinel
# compares the newest history row against the trailing rows with the SAME
# (n_cores, backend, knob-set) key, so a laptop and CI keep separate
# baselines in one file; the first run on a fresh machine passes by default
# (no baseline yet).

set -o pipefail
cd "$(dirname "$0")/.."

echo "== native ingest engine: build from source =="
# The checked-in .so must never go stale against the grown C API: rebuild
# from source when a compiler is present (a build FAILURE is fatal — it
# means rdfind_native.cpp no longer compiles); skip gracefully on
# compiler-less boxes (the Python fallback path still runs under tier-1,
# and io/native.py's _bind AttributeErrors a stale .so into that fallback).
if command -v "${CXX:-g++}" >/dev/null 2>&1; then
    if ! make -C native; then
        echo "verify: native build FAILED" >&2
        exit 1
    fi
else
    echo "verify: no C++ compiler (${CXX:-g++}); native build skipped"
fi

echo "== tier-1 test suite (ROADMAP recipe) =="
rm -f /tmp/_t1.log
timeout -k 10 2400 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "verify: tier-1 suite FAILED (rc=$rc)" >&2
    exit "$rc"
fi

echo "== run console smoke (--console-port 0) =="
# Tiny CPU run with an ephemeral console port: fetch /metrics + /progress
# while it executes and assert both parse.  Skips itself (exit 0) when the
# sandbox forbids loopback listening; VERIFY_SKIP_CONSOLE=1 skips outright.
if [ "${VERIFY_SKIP_CONSOLE:-0}" = "1" ]; then
    echo "verify: console smoke skipped (VERIFY_SKIP_CONSOLE=1)"
elif ! JAX_PLATFORMS=cpu timeout -k 10 600 python scripts/console_smoke.py; then
    echo "verify: console smoke FAILED" >&2
    exit 1
fi

echo "== rung-3 kernel parity (planes {8,4,2} x emit on/off) =="
# Every knob combination must produce bit-identical containment outputs and
# dense CIND pair sets on a tiny planted workload — knobs move schedules,
# never results.  VERIFY_SKIP_KERNEL_RUNGS=1 opts out.
if [ "${VERIFY_SKIP_KERNEL_RUNGS:-0}" = "1" ]; then
    echo "verify: kernel-rung parity skipped (VERIFY_SKIP_KERNEL_RUNGS=1)"
elif ! JAX_PLATFORMS=cpu timeout -k 10 600 python scripts/kernel_rung_parity.py; then
    echo "verify: kernel-rung parity FAILED" >&2
    exit 1
fi

echo "== sharded half-approx parity (RDFIND_SHARDED_HALF_APPROX on/off) =="
# The distributed two-round count-min cut must be bit-identical to the
# exact path on a tiny planted workload (mesh 8 flat + 2-host hierarchical
# sketch reduce, which must also cut DCN bytes).  The knob only moves
# bytes, never results.  VERIFY_SKIP_HALF_APPROX=1 opts out.
if [ "${VERIFY_SKIP_HALF_APPROX:-0}" = "1" ]; then
    echo "verify: half-approx parity skipped (VERIFY_SKIP_HALF_APPROX=1)"
elif ! JAX_PLATFORMS=cpu timeout -k 10 900 python scripts/half_approx_parity.py; then
    echo "verify: half-approx parity FAILED" >&2
    exit 1
fi

echo "== elastic-resume parity (preempt at mesh 8, resume at mesh 2) =="
# Mesh-portable snapshots: a preempted run resumed on a different mesh size
# must replay its committed passes and stay bit-identical to a clean run
# (both shrink and grow directions).  VERIFY_SKIP_ELASTIC=1 opts out.
if [ "${VERIFY_SKIP_ELASTIC:-0}" = "1" ]; then
    echo "verify: elastic-resume parity skipped (VERIFY_SKIP_ELASTIC=1)"
elif ! JAX_PLATFORMS=cpu timeout -k 10 900 python scripts/elastic_resume_parity.py; then
    echo "verify: elastic-resume parity FAILED" >&2
    exit 1
fi

echo "== integrity parity (digests on/off, planted flip, verified resume) =="
# The integrity plane must never change results: knob on/off bit-identical,
# a planted bit flip detected+named+repaired, and an 8 -> 2 resume with
# every snapshot pass digest-verified.  VERIFY_SKIP_INTEGRITY=1 opts out.
if [ "${VERIFY_SKIP_INTEGRITY:-0}" = "1" ]; then
    echo "verify: integrity parity skipped (VERIFY_SKIP_INTEGRITY=1)"
elif ! JAX_PLATFORMS=cpu timeout -k 10 900 python scripts/integrity_parity.py; then
    echo "verify: integrity parity FAILED" >&2
    exit 1
fi

echo "== delta parity (base -> planted insert+delete batch -> --delta) =="
# Incremental discovery must be bit-identical to from-scratch on the updated
# dataset, chain its certificate onto the base run, and actually reuse
# passes (proportional-to-change).  VERIFY_SKIP_DELTA=1 opts out.
if [ "${VERIFY_SKIP_DELTA:-0}" = "1" ]; then
    echo "verify: delta parity skipped (VERIFY_SKIP_DELTA=1)"
elif ! JAX_PLATFORMS=cpu timeout -k 10 900 python scripts/delta_parity.py; then
    echo "verify: delta parity FAILED" >&2
    exit 1
fi

echo "== watchdog parity (injected wedge -> bounded recoverable preemption) =="
# A wedged collective must convert to Preempted within the watchdog timeout
# (never an indefinite stall), flush committed passes, and the re-entered
# run must resume bit-identical.  VERIFY_SKIP_WATCHDOG=1 opts out.
if [ "${VERIFY_SKIP_WATCHDOG:-0}" = "1" ]; then
    echo "verify: watchdog parity skipped (VERIFY_SKIP_WATCHDOG=1)"
elif ! JAX_PLATFORMS=cpu timeout -k 10 900 python scripts/watchdog_parity.py; then
    echo "verify: watchdog parity FAILED" >&2
    exit 1
fi

echo "== serve parity (index answers vs discovery output + hot swap) =="
# The mmap'd CIND index must answer bit-consistently with the run that
# wrote it (all four strategies), and a delta-committed generation must
# hot-swap with answers identical to a from-scratch index (corrupt
# candidates refused by section name).  VERIFY_SKIP_SERVE=1 opts out.
if [ "${VERIFY_SKIP_SERVE:-0}" = "1" ]; then
    echo "verify: serve parity skipped (VERIFY_SKIP_SERVE=1)"
elif ! JAX_PLATFORMS=cpu timeout -k 10 900 python scripts/serve_parity.py; then
    echo "verify: serve parity FAILED" >&2
    exit 1
fi

echo "== serve observability parity (live admin plane: /metrics, SLOs) =="
# The live serving process's telemetry must be exact (Prometheus scrape
# counts == fired requests, malformed traffic counted), planted slow/stale
# conditions must burn the NAMED SLO on /slo + heartbeat + tpu_watch, and
# answers must be byte-identical with obs off.  VERIFY_SKIP_SERVE_OBS=1
# opts out.
if [ "${VERIFY_SKIP_SERVE_OBS:-0}" = "1" ]; then
    echo "verify: serve obs parity skipped (VERIFY_SKIP_SERVE_OBS=1)"
elif ! JAX_PLATFORMS=cpu timeout -k 10 900 python scripts/serve_obs_parity.py; then
    echo "verify: serve obs parity FAILED" >&2
    exit 1
fi

if [ "${VERIFY_SKIP_BENCH:-0}" = "1" ]; then
    echo "verify: tier-1 green; bench + sentinel skipped (VERIFY_SKIP_BENCH=1)"
    exit 0
fi

echo "== tiny bench -> BENCH_HISTORY -> regression sentinel =="
hist="${BENCH_HISTORY:-BENCH_HISTORY.jsonl}"
if ! BENCH_BACKEND=cpu JAX_PLATFORMS=cpu \
     BENCH_TRIPLES="${VERIFY_BENCH_TRIPLES:-400}" BENCH_MIN_SUPPORT=2 \
     BENCH_PIPELINE_TRIPLES=600 BENCH_EXCHANGE_TRIPLES=600 \
     BENCH_HISTORY="$hist" \
     timeout -k 10 1800 python bench.py > /tmp/_verify_bench.json; then
    echo "verify: tiny bench FAILED (see /tmp/_verify_bench.json)" >&2
    exit 1
fi
if ! python -m rdfind_tpu.obs.sentinel --check --history "$hist"; then
    exit 1
fi

echo "== tiny delta bench -> BENCH_HISTORY -> regression sentinel =="
# Incremental-discovery speedup rows (delta_speedup_*, frac_passes_rerun):
# the proportional-to-change claim, regression-gated like every other
# metric.  Appends to the SAME history file; the rows carry a distinct
# workload stamp so output digests never cross-compare with bench.py's.
if ! BENCH_BACKEND=cpu JAX_PLATFORMS=cpu \
     BENCH_DELTA_TRIPLES="${VERIFY_BENCH_DELTA_TRIPLES:-1200}" \
     BENCH_HISTORY="$hist" \
     timeout -k 10 1800 python bench_delta.py > /tmp/_verify_bench_delta.json; then
    echo "verify: tiny delta bench FAILED (see /tmp/_verify_bench_delta.json)" >&2
    exit 1
fi
if ! python -m rdfind_tpu.obs.sentinel --check --history "$hist"; then
    exit 1
fi

echo "== serve bench -> BENCH_HISTORY -> regression sentinel =="
# Query-plane rows (serve_qps / serve_open_ms / serve_p99_us): the mmap'd
# index's open must stay O(header) and holds() must stay >= the QPS floor;
# regressions gate like kernel regressions.
if ! BENCH_BACKEND=cpu JAX_PLATFORMS=cpu \
     BENCH_HISTORY="$hist" \
     timeout -k 10 900 python bench_serve.py > /tmp/_verify_bench_serve.json; then
    echo "verify: serve bench FAILED (see /tmp/_verify_bench_serve.json)" >&2
    exit 1
fi
python -m rdfind_tpu.obs.sentinel --check --history "$hist"
