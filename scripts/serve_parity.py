"""Serving parity gate: index answers bit-consistent with discovery output.

CPU-proxy workload; three checks:

  1. answer parity, all four traversal strategies — a run that persists a
     bundle (--delta-state) commits a generation-0 index next to it; every
     CIND in the run's table must answer holds=true (and referenced() must
     return exactly the table's refset), and sampled non-CIND pairs must
     answer false — oracle-checked against the in-memory table, through the
     STRING capture path (so dictionary/interner parity is covered, not
     just id plumbing);
  2. hot-swap differential — an IndexService serving generation 0 polls
     after a --delta run advances the bundle; the swap must verify + chain
     (history gen 0 -> 1) and the swapped answers must be identical to a
     from-scratch index built by a clean run on the updated dataset;
  3. integrity wiring — a flipped byte in a committed index is refused by
     the service with the section named, and the old generation keeps
     serving.

scripts/verify.sh runs this before the bench gate; VERIFY_SKIP_SERVE=1
opts out.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["RDFIND_BACKOFF_BASE_MS"] = "1"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _capture_strings(table, dictionary, row):
    """(dep, ref) string captures of table row `row`."""
    def dec(v):
        return None if int(v) < 0 else dictionary.value(int(v))
    dep = (int(table.dep_code[row]), dec(table.dep_v1[row]),
           dec(table.dep_v2[row]))
    ref = (int(table.ref_code[row]), dec(table.ref_v1[row]),
           dec(table.ref_v2[row]))
    return dep, ref


def _answers(reader):
    """The index's full CIND answer set as id triples (for differentials
    the two indexes share a value space by construction: same sorted
    dictionary -> same ranks)."""
    return set(reader.iter_cinds())


def main() -> int:
    from rdfind_tpu.runtime import driver, serving
    from rdfind_tpu.utils import synth

    failures = []
    support = 3
    triples = synth.generate_triples(900, seed=3)
    ins, dels = synth.grow_delta_batches(triples, 0.01, seed=4)

    with tempfile.TemporaryDirectory() as root:
        paths = {k: os.path.join(root, f"{k}.nt")
                 for k in ("base", "ins", "del", "upd")}
        synth.write_nt(paths["base"], triples)
        synth.write_nt(paths["ins"], ins)
        synth.write_nt(paths["del"], dels)
        synth.write_nt(paths["upd"], synth.apply_delta(triples, ins, dels))
        rng = np.random.default_rng(11)

        # --- 1. answer parity, all four strategies -------------------------
        for strat in (0, 1, 2, 3):
            bundle = os.path.join(root, f"bundle{strat}")
            res = driver.run(driver.Config(
                input_paths=[paths["base"]], min_support=support,
                traversal_strategy=strat, delta_state=bundle))
            try:
                reader = serving.IndexReader(serving.index_path(bundle))
            except serving.IndexMiss as e:
                failures.append(f"strategy {strat}: no index emitted ({e})")
                continue
            v = reader.verify()
            if not v["ok"]:
                failures.append(f"strategy {strat}: fresh index fails "
                                f"verification: {v['mismatches']}")
            table, dic = res.table, res.dictionary
            if not len(table):
                failures.append(f"strategy {strat}: empty table "
                                "(gate is vacuous)")
            truth = set()
            for row in range(len(table)):
                dep, ref = _capture_strings(table, dic, row)
                truth.add((dep, ref))
                if not reader.holds(dep, ref):
                    failures.append(f"strategy {strat}: CIND row {row} "
                                    f"{dep} < {ref} answers holds=false")
                    break
                want_sup = int(table.support[row])
                if reader.support(dep) != want_sup:
                    failures.append(
                        f"strategy {strat}: support({dep}) = "
                        f"{reader.support(dep)} != {want_sup}")
                    break
            # referenced() completeness for one sampled dependent.
            row = int(rng.integers(0, len(table)))
            dep, _ = _capture_strings(table, dic, row)
            got_refs = set(reader.referenced(dep))
            want_refs = {r for d, r in truth if d == dep}
            if got_refs != want_refs:
                failures.append(
                    f"strategy {strat}: referenced({dep}) returned "
                    f"{len(got_refs)} captures, table says "
                    f"{len(want_refs)}")
            # top-k ordering: nonincreasing support, first == max.
            tk = reader.topk(min(10, reader.n_cinds), decode=False)
            sups = [s for _, _, s in tk]
            if sups != sorted(sups, reverse=True) or (
                    sups and sups[0] != int(np.max(table.support))):
                failures.append(f"strategy {strat}: topk support order "
                                f"broken: {sups}")
            # Sampled non-CIND pairs must answer false.
            deps = sorted({d for d, _ in truth})
            refs = sorted({r for _, r in truth})
            checked = 0
            for _ in range(500):
                d = deps[int(rng.integers(0, len(deps)))]
                r = refs[int(rng.integers(0, len(refs)))]
                if (d, r) in truth or d == r:
                    continue
                checked += 1
                if reader.holds(d, r):
                    failures.append(f"strategy {strat}: non-CIND pair "
                                    f"{d} < {r} answers holds=true")
                    break
            if checked == 0:
                failures.append(f"strategy {strat}: no negative pairs "
                                "sampled (gate is vacuous)")
            reader.close()

        # --- 2. delta hot-swap differential --------------------------------
        bundle = os.path.join(root, "bundle0")  # strategy-0 gen-0 bundle
        svc = serving.IndexService(bundle)
        v0 = svc.poll()
        if v0.get("action") != "swapped" or svc.generation != 0:
            failures.append(f"service did not load generation 0: {v0}")
        with svc.acquire() as r:
            gen0_answers = _answers(r) if r else set()
        res_delta = driver.run(driver.Config(
            input_paths=[paths["ins"]], delete_paths=[paths["del"]],
            min_support=support, traversal_strategy=0, delta_base=bundle))
        v1 = svc.poll()
        if v1.get("action") != "swapped" or svc.generation != 1:
            failures.append(f"hot swap to generation 1 failed: {v1}, "
                            f"pending={svc.pending}")
        if [c["generation"] for c in svc.chain] != [0, 1]:
            failures.append(f"swap history chain wrong: {svc.chain}")

        scratch_dir = os.path.join(root, "scratch_bundle")
        driver.run(driver.Config(
            input_paths=[paths["upd"]], min_support=support,
            traversal_strategy=0, delta_state=scratch_dir))
        scratch = serving.IndexReader(serving.index_path(scratch_dir))
        with svc.acquire() as r:
            swapped_answers = _answers(r)
            swapped_digest = r.output_digest
        scratch_answers = _answers(scratch)
        if swapped_answers != scratch_answers:
            failures.append(
                f"hot-swapped answers differ from from-scratch index: "
                f"{len(swapped_answers ^ scratch_answers)} rows")
        if swapped_digest != scratch.output_digest:
            failures.append(
                f"swapped output digest {swapped_digest} != from-scratch "
                f"{scratch.output_digest}")
        if swapped_answers == gen0_answers:
            failures.append("generation 1 answers identical to generation "
                            "0 — the differential is vacuous")
        scratch.close()

        # --- 3. corrupted candidate refused, old generation kept -----------
        path = serving.index_path(bundle)
        blob = bytearray(open(path, "rb").read())
        meta_reader = serving.IndexReader(path)
        sec = meta_reader.meta["sections"][0]
        meta_reader.close()
        blob[int(sec["offset"])] ^= 0xFF
        with open(path, "wb") as f:
            f.write(blob)
        os.utime(path, ns=(1, 1))  # force the stat key to change
        v2 = svc.poll()
        if v2.get("action") != "refused" or \
                v2.get("reason") != "section-digest-mismatch" or \
                sec["name"] not in v2.get("sections", []):
            failures.append(f"corrupt index not refused by name: {v2}")
        if svc.generation != 1:
            failures.append(f"service abandoned generation 1 after a "
                            f"corrupt candidate (now {svc.generation})")
        with svc.acquire() as r:
            if r is None or _answers(r) != swapped_answers:
                failures.append("old generation stopped answering after a "
                                "refused swap")
        svc.close()
        del res_delta

    if failures:
        for f in failures:
            print(f"serve_parity: {f}", file=sys.stderr)
        return 1
    print("serve_parity: OK — index answers match discovery output for "
          "strategies 0-3 (holds/referenced/support/topk, sampled "
          "negatives false), delta gen 0 -> 1 hot-swap chained and "
          "bit-identical to a from-scratch index, corrupt candidate "
          "refused by section name with the old generation still serving")
    return 0


if __name__ == "__main__":
    sys.exit(main())
