"""Delta-discovery parity gate (incremental CIND maintenance).

Planted-CIND workload grown with an insert+delete batch on the CPU proxy;
four checks:

  1. bit-identity — a base run that persists a bundle (--delta-state), then
     a --delta replay of a ~1% batch, must write byte-identical output to a
     from-scratch run on the updated dataset (strategies 0 and 1: one
     no-filter raw path, one filtered raw path);
  2. incrementality — the delta run takes the incremental path and re-runs
     only a strict subset of the pass partition (passes_reused > 0);
  3. certificate chaining — the delta run's certificate carries
     base_output_digest == the base run's certificate output_digest and the
     advanced generation;
  4. digest plumbing — the bundle written by the delta run reloads with
     zero degradations (every stage digest verifies).

scripts/verify.sh runs this before the bench gate; VERIFY_SKIP_DELTA=1
opts out.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["RDFIND_BACKOFF_BASE_MS"] = "1"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from rdfind_tpu.programs import rdfind
    from rdfind_tpu.runtime import delta, driver
    from rdfind_tpu.utils import synth

    failures = []
    support = 3
    triples = synth.generate_triples(900, seed=3)
    ins, dels = synth.grow_delta_batches(triples, 0.01, seed=4)

    with tempfile.TemporaryDirectory() as root:
        paths = {k: os.path.join(root, f"{k}.nt")
                 for k in ("base", "ins", "del", "upd")}
        synth.write_nt(paths["base"], triples)
        synth.write_nt(paths["ins"], ins)
        synth.write_nt(paths["del"], dels)
        synth.write_nt(paths["upd"], synth.apply_delta(triples, ins, dels))
        cert_base = os.path.join(root, "cert_base.json")
        cert_delta = os.path.join(root, "cert_delta.json")
        os.environ["RDFIND_INTEGRITY"] = "1"

        for strat in ("0", "1"):
            bundle = os.path.join(root, f"bundle{strat}")
            o_delta = os.path.join(root, "out_delta.txt")
            o_scratch = os.path.join(root, "out_scratch.txt")
            common = ["--support", str(support),
                      "--traversal-strategy", strat]

            os.environ["RDFIND_CERT"] = cert_base
            if rdfind.main([paths["base"], *common,
                            "--delta-state", bundle]) != 0:
                failures.append(f"strategy {strat}: base run failed")
                continue
            os.environ["RDFIND_CERT"] = cert_delta
            if rdfind.main([paths["ins"], "--delta", bundle,
                            "--deletes", paths["del"], *common,
                            "--output", o_delta]) != 0:
                failures.append(f"strategy {strat}: delta run failed")
                continue
            os.environ.pop("RDFIND_CERT", None)
            if rdfind.main([paths["upd"], *common,
                            "--output", o_scratch]) != 0:
                failures.append(f"strategy {strat}: scratch run failed")
                continue

            got, want = open(o_delta).read(), open(o_scratch).read()
            if got != want:
                diff = sorted(set(got.splitlines())
                              ^ set(want.splitlines()))
                failures.append(
                    f"strategy {strat}: delta output is not bit-identical "
                    f"({len(diff)} differing rows, e.g. {diff[:3]})")
            if not want.strip():
                failures.append(
                    f"strategy {strat}: empty output (gate is vacuous)")

            cb = json.load(open(cert_base))
            cd = json.load(open(cert_delta))
            if cd.get("base_output_digest") != cb.get("output_digest"):
                failures.append(
                    f"strategy {strat}: certificate chain broken "
                    f"({cd.get('base_output_digest')} != base "
                    f"{cb.get('output_digest')})")
            if cd.get("generation") != 1:
                failures.append(f"strategy {strat}: delta certificate "
                                f"generation {cd.get('generation')} != 1")

            # Reload the advanced bundle: every stage digest must verify.
            b = delta.load_bundle(bundle, min_support=support,
                                  projections="spo", distinct=False)
            if b.degraded:
                failures.append(f"strategy {strat}: advanced bundle "
                                f"degraded on reload: {b.degraded}")
            if int(b.meta["generation"]) != 1:
                failures.append(f"strategy {strat}: bundle generation "
                                f"{b.meta['generation']} != 1")

        # Incrementality: pass reuse visible in the stats fan-out.
        bundle = os.path.join(root, "bundle0")
        res = driver.run(driver.Config(
            input_paths=[paths["ins"]], delete_paths=[paths["del"]],
            min_support=support, traversal_strategy=0, delta_base=bundle))
        st = res.counters.get("stat-delta", {})
        if st.get("path") != "incremental":
            failures.append(f"delta took path {st.get('path')!r}, "
                            "expected 'incremental'")
        if not (0 < st.get("passes_rerun", 0) < st.get("n_passes", 0)):
            failures.append(
                f"no pass reuse: reran {st.get('passes_rerun')} of "
                f"{st.get('n_passes')} passes")
        os.environ.pop("RDFIND_INTEGRITY", None)

    if failures:
        for f in failures:
            print(f"delta_parity: {f}", file=sys.stderr)
        return 1
    print(f"delta_parity: OK — 1% batch bit-identical via --delta "
          f"(strategies 0+1), certificate chained gen 0 -> 1, "
          f"{st['passes_rerun']}/{st['n_passes']} passes re-run "
          f"({st['passes_reused']} reused), advanced bundle "
          "digest-verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
