"""Sharded half-approximate 1/1 parity gate (RDFIND_SHARDED_HALF_APPROX).

Tiny planted workload on the CPU proxy (8 fake devices): the sharded S2L
and Approximate strategies must produce bit-identical CIND rows with the
two-round count-min cut on vs off, at mesh 8 flat AND under the 2-host
hierarchical sketch reduction — where the ledger must also show the
factor-`local` DCN byte reduction of the hierarchical all-reduce.  The
device-side saturating reduction is differentially checked against host
`merge_count_min` at saturation on the way.  scripts/verify.sh runs this
next to kernel_rung_parity; VERIFY_SKIP_HALF_APPROX=1 opts out.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _set(name, value):
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = value


def main() -> int:
    from rdfind_tpu.models import sharded
    from rdfind_tpu.ops import sketch
    from rdfind_tpu.parallel import exchange
    from rdfind_tpu.parallel.mesh import make_mesh
    from rdfind_tpu.utils.synth import generate_planted_cinds

    for var in ("RDFIND_SHARDED_HALF_APPROX", "RDFIND_SHARDED_HA_BITS",
                "RDFIND_HIER_HOSTS", "RDFIND_HIER_EXCHANGE"):
        _set(var, None)

    failures = []
    mesh = make_mesh(8)
    triples, _ = generate_planted_cinds(6, 8, seed=3)

    # --- Saturation differential: device reduce vs host merge at the cap.
    rng = np.random.default_rng(0)
    cap = sketch.MAX_COUNT_MIN_CAP
    parts = [np.asarray(sketch.count_min_partial(
        rng.integers(0, 40, 200).astype(np.int32),
        rng.integers(cap // 3, cap // 2, 200).astype(np.int32),
        np.ones(200, bool), bits=256, num_hashes=2)) for _ in range(8)]
    ref_tbl = sketch.merge_count_min(parts)
    import functools
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from rdfind_tpu.parallel.mesh import AXIS, shard_map
    for hier in (None, (2, 4)):
        fn = functools.partial(exchange.sketch_allreduce, axis_name=AXIS,
                               cap=cap, hier=hier)
        got = np.asarray(jax.jit(shard_map(
            lambda t: fn(jnp.reshape(t, (-1,))), mesh=mesh,
            in_specs=(P(AXIS),), out_specs=P(AXIS),
            check_vma=False))(np.stack(parts))).reshape(8, -1)[0]
        if not np.array_equal(ref_tbl, got):
            failures.append(f"sketch_allreduce(hier={hier}) != "
                            "merge_count_min at saturation")

    # --- Bit-identity: knob on vs off, both strategies, flat and 2-host.
    strategies = [("s2l", sharded.discover_sharded_s2l),
                  ("approx", sharded.discover_sharded_approx)]
    n_rows = {}
    for name, fn in strategies:
        _set("RDFIND_SHARDED_HALF_APPROX", None)
        ref = fn(triples, 2, mesh=mesh).to_rows()
        n_rows[name] = len(ref)
        if not ref:
            failures.append(f"{name}: planted workload produced 0 CINDs "
                            "(gate is vacuous)")
        _set("RDFIND_SHARDED_HALF_APPROX", "1")
        stats = {}
        if fn(triples, 2, mesh=mesh, stats=stats).to_rows() != ref:
            failures.append(f"{name}: knob-on output differs at mesh 8")
        if stats.get("ha_build_rounds", 0) < 1:
            failures.append(f"{name}: knob on but no sketch build ran")

    # --- Hierarchical reduce: same rows, measurably fewer DCN bytes.
    _set("RDFIND_SHARDED_HALF_APPROX", "1")
    _set("RDFIND_HIER_HOSTS", "2")
    ref = None
    dcn = {}
    for mode in ("0", "1"):
        _set("RDFIND_HIER_EXCHANGE", mode)
        stats = {}
        rows = sharded.discover_sharded_s2l(triples, 2, mesh=mesh,
                                            stats=stats).to_rows()
        if ref is None:
            ref = rows
        elif rows != ref:
            failures.append("hier sketch reduce changed the output")
        site = stats.get("exchange_sites", {}).get(
            exchange.SKETCH_ALLREDUCE_SITE, {})
        dcn[mode] = site.get("dcn_bytes", -1)
    if not (0 <= dcn["1"] < dcn["0"]):
        failures.append(f"hier sketch reduce did not cut DCN bytes "
                        f"(flat={dcn['0']}, hier={dcn['1']})")

    if failures:
        for f in failures:
            print(f"half_approx_parity: {f}", file=sys.stderr)
        return 1
    print(f"half_approx_parity: OK — {n_rows} CIND rows bit-identical with "
          f"the two-round cut on/off (mesh 8 flat + 2-host hier), sketch "
          f"DCN bytes {dcn['0']} -> {dcn['1']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
