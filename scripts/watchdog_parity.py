"""Collective-watchdog parity gate (wedge -> recoverable preemption).

Tiny workload on the CPU proxy (8 fake devices): a wedge is injected inside
the pass executor's armed counters pull (wedge@pairs) with a small watchdog
floor — the deadman must convert the hang into Preempted within a bounded
burn (never the indefinite stall it replaces), flush the committed passes,
and a re-entered run must resume (resumed_passes > 0) bit-identical to a
never-wedged single-device reference.  The degradation ledger must carry
the wedged@pairs stamp and the watchdog counters must land in stats.
scripts/verify.sh runs this next to elastic_resume_parity;
VERIFY_SKIP_WATCHDOG=1 opts out.
"""

import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# Small pass budget so the wedge lands mid-phase with passes to resume.
os.environ["RDFIND_PAIR_ROW_BUDGET"] = "8192"
os.environ["RDFIND_BACKOFF_BASE_MS"] = "1"
os.environ["RDFIND_WATCHDOG"] = "1"
# Bounded burn: generous against cold-compile stalls inside armed windows
# (this gate compiles its programs from scratch), tiny against the
# multi-hour hang a real wedge used to cost.
os.environ["RDFIND_COLLECTIVE_TIMEOUT_S"] = "30"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from rdfind_tpu.models import allatonce, sharded
    from rdfind_tpu.parallel.mesh import make_mesh
    from rdfind_tpu.runtime import checkpoint, faults, watchdog
    from rdfind_tpu.utils.synth import generate_triples

    failures = []
    triples = generate_triples(300, seed=21, n_predicates=8, n_entities=32)
    ref = allatonce.discover(triples, 2).to_rows()
    if not ref:
        failures.append("workload produced 0 CINDs (gate is vacuous)")
    mesh = make_mesh(8)
    # Warm the jit cache so the wedged run's armed windows hold collectives,
    # not compiles — the burn bound below then measures the watchdog, and a
    # legitimately slow compile cannot false-fire the 30 s floor.
    sharded.discover_sharded(triples, 2, mesh=mesh)

    with tempfile.TemporaryDirectory() as root:
        def progress():
            return checkpoint.ProgressStore(
                checkpoint.CheckpointStore(os.path.join(root, "w")), "base")

        # 3rd pairs-guard hit = pass 1 counters (counters + blocks per
        # pass): pass 0 has committed, so the resume must skip it.
        os.environ["RDFIND_FAULTS"] = "wedge@pairs:nth=3"
        faults.reset()
        watchdog.reset()
        stats = {}
        t0 = time.monotonic()
        try:
            sharded.discover_sharded(triples, 2, mesh=mesh, stats=stats,
                                     progress=progress())
            failures.append("planted wedge never fired")
        except faults.Preempted:
            burn = time.monotonic() - t0
            if burn > 120.0:
                failures.append(f"wedge burn {burn:.0f}s is not bounded by "
                                "the watchdog timeout")
        finally:
            os.environ.pop("RDFIND_FAULTS", None)
            faults.reset()

        degr = [d for d in stats.get("degradations", [])
                if d.get("phase") == "watchdog"]
        if not degr or degr[-1].get("action") != "wedged@pairs":
            failures.append(f"degradation ledger missing wedged@pairs "
                            f"({stats.get('degradations')})")
        if not watchdog.fired("pairs"):
            failures.append("watchdog.fired('pairs') is False after the fire")

        # Supervisor protocol, then the re-entered attempt.
        watchdog.clear_fired()
        watchdog.clear_markers()
        s2 = {}
        rows = sharded.discover_sharded(triples, 2, mesh=mesh, stats=s2,
                                        progress=progress()).to_rows()
        if s2.get("resumed_passes", 0) < 1:
            failures.append("re-entered run resumed no committed passes "
                            "(the fire path must flush progress)")
        wd = s2.get("watchdog", {})
        if wd.get("fired", 0) < 1:
            failures.append(f"stats['watchdog'] counters missing ({wd})")
        if rows != ref:
            failures.append("recovered CIND table differs from the "
                            "never-wedged reference")

    if failures:
        for f in failures:
            print(f"watchdog_parity: {f}", file=sys.stderr)
        return 1
    print(f"watchdog_parity: OK — wedge@pairs converted to Preempted, "
          f"re-entry resumed committed passes, {len(ref)} CIND rows "
          "bit-identical")
    return 0


if __name__ == "__main__":
    sys.exit(main())
