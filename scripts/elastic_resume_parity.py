"""Elastic-resume parity gate (mesh-portable per-pass snapshots).

Tiny workload on the CPU proxy (8 fake devices): a sharded discover is
preempted mid-pass at mesh 8 and resumed at mesh 2 — the re-shard-on-load
path must replay the committed passes (resumed_passes > 0, elastic_resume
counters populated) and the final CIND table must stay bit-identical to a
never-preempted single-device run.  The grow direction (1 -> 8) is checked
the same way.  scripts/verify.sh runs this next to half_approx_parity;
VERIFY_SKIP_ELASTIC=1 opts out.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# Small pass budget so the preemption lands mid-phase with passes to resume.
os.environ["RDFIND_PAIR_ROW_BUDGET"] = "8192"
os.environ["RDFIND_BACKOFF_BASE_MS"] = "1"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from rdfind_tpu.models import allatonce, sharded
    from rdfind_tpu.parallel.mesh import make_mesh
    from rdfind_tpu.runtime import checkpoint, faults
    from rdfind_tpu.utils.synth import generate_triples

    failures = []
    triples = generate_triples(300, seed=21, n_predicates=8, n_entities=32)
    ref = allatonce.discover(triples, 2).to_rows()
    if not ref:
        failures.append("workload produced 0 CINDs (gate is vacuous)")

    def progress(root, name):
        return checkpoint.ProgressStore(
            checkpoint.CheckpointStore(os.path.join(root, name)), "base")

    with tempfile.TemporaryDirectory() as root:
        for tag, from_dev, to_dev in (("shrink", 8, 2), ("grow", 1, 8)):
            os.environ["RDFIND_FAULTS"] = "preempt@discover:pass=1"
            faults.reset()
            try:
                sharded.discover_sharded(triples, 2, mesh=make_mesh(from_dev),
                                         progress=progress(root, tag))
                failures.append(f"{tag}: planted preemption never fired")
                continue
            except faults.Preempted:
                pass
            finally:
                os.environ.pop("RDFIND_FAULTS", None)
                faults.reset()

            stats = {}
            rows = sharded.discover_sharded(
                triples, 2, mesh=make_mesh(to_dev), stats=stats,
                progress=progress(root, tag)).to_rows()
            if stats.get("resumed_passes", 0) < 1:
                failures.append(f"{tag}: resume replayed no committed passes")
            er = stats.get("elastic_resume", {})
            if (er.get("from_num_dev"), er.get("to_num_dev")) != (from_dev,
                                                                  to_dev):
                failures.append(f"{tag}: elastic_resume mesh trace missing "
                                f"or wrong ({er})")
            if rows != ref:
                failures.append(f"{tag}: resumed CIND table differs from the "
                                "never-preempted reference")

    if failures:
        for f in failures:
            print(f"elastic_resume_parity: {f}", file=sys.stderr)
        return 1
    print(f"elastic_resume_parity: OK — {len(ref)} CIND rows bit-identical "
          "across preempt-at-mesh-8/resume-at-mesh-2 and the 1 -> 8 grow "
          "direction")
    return 0


if __name__ == "__main__":
    sys.exit(main())
