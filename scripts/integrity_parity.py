"""Integrity-plane parity gate (order/mesh-invariant stage digests).

Tiny planted workload on the CPU proxy (8 fake devices), three checks:

  1. knob parity — RDFIND_INTEGRITY=0 and =1 sharded runs are bit-identical
     (the device digest lanes are computed unconditionally; only host-side
     verification is gated) and the on-run's published ``output`` stage
     digest matches an independently computed digest of the reference table;
  2. flip detection — a planted ``flip@host_pull`` bit flip is DETECTED AND
     NAMED (site + pass) and repaired by re-pull, output still bit-identical;
  3. digest-attested resume — preempted at mesh 8, resumed at mesh 2 with
     integrity on: every loaded snapshot pass re-verifies after the re-shard
     (verified > 0, mismatches == 0) and the table stays bit-identical.

scripts/verify.sh runs this next to elastic_resume_parity;
VERIFY_SKIP_INTEGRITY=1 opts out.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# Small pass budget so the run has several passes to verify and resume.
os.environ["RDFIND_PAIR_ROW_BUDGET"] = "8192"
os.environ["RDFIND_BACKOFF_BASE_MS"] = "1"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from rdfind_tpu.models import allatonce, sharded
    from rdfind_tpu.obs import integrity
    from rdfind_tpu.parallel.mesh import make_mesh
    from rdfind_tpu.runtime import checkpoint, faults
    from rdfind_tpu.utils.synth import generate_triples

    failures = []
    triples = generate_triples(300, seed=21, n_predicates=8, n_entities=32)
    ref_table = allatonce.discover(triples, 2)
    ref = ref_table.to_rows()
    if not ref:
        failures.append("workload produced 0 CINDs (gate is vacuous)")
    ref_digest = integrity.digest_hex(*integrity.digest_table(ref_table))
    mesh8 = make_mesh(8)

    # 1. Knob on/off bit-identity + the published output-stage digest.
    os.environ["RDFIND_INTEGRITY"] = "0"
    off = sharded.discover_sharded(triples, 2, mesh=mesh8).to_rows()
    os.environ["RDFIND_INTEGRITY"] = "1"
    stats_on = {}
    on = sharded.discover_sharded(triples, 2, mesh=mesh8,
                                  stats=stats_on).to_rows()
    if off != on or on != ref:
        failures.append("knob parity: RDFIND_INTEGRITY on/off tables differ")
    stages = stats_on.get("integrity_stages", {})
    if stages.get("output") != ref_digest:
        failures.append(f"knob parity: published output digest "
                        f"{stages.get('output')} != reference {ref_digest}")
    if stats_on.get("integrity_mismatches", 0):
        failures.append("knob parity: clean run reported digest mismatches")

    # 2. A planted host-pull bit flip: detected, named, repaired.
    os.environ["RDFIND_FAULTS"] = "flip@host_pull:nth=1"
    faults.reset()
    stats_flip = {}
    flipped = sharded.discover_sharded(triples, 2, mesh=mesh8,
                                       stats=stats_flip).to_rows()
    os.environ.pop("RDFIND_FAULTS", None)
    faults.reset()
    events = [e for e in stats_flip.get("integrity_events", [])
              if e.get("site") == "host_pull"]
    if not events:
        failures.append("flip: planted host_pull flip was never detected")
    elif not (events[0].get("repaired") and "pass" in events[0]
              and events[0].get("stage")):
        failures.append(f"flip: event not named/repaired: {events[0]}")
    if flipped != ref:
        failures.append("flip: repaired run is not bit-identical")

    # 3. Digest-attested 8 -> 2 resume.
    with tempfile.TemporaryDirectory() as root:
        def progress():
            return checkpoint.ProgressStore(
                checkpoint.CheckpointStore(os.path.join(root, "r")), "base")

        os.environ["RDFIND_FAULTS"] = "preempt@discover:pass=1"
        faults.reset()
        try:
            sharded.discover_sharded(triples, 2, mesh=mesh8,
                                     progress=progress())
            failures.append("resume: planted preemption never fired")
        except faults.Preempted:
            pass
        finally:
            os.environ.pop("RDFIND_FAULTS", None)
            faults.reset()
        stats_res = {}
        rows = sharded.discover_sharded(triples, 2, mesh=make_mesh(2),
                                        stats=stats_res,
                                        progress=progress()).to_rows()
        if stats_res.get("resumed_passes", 0) < 1:
            failures.append("resume: no committed passes were replayed")
        if not stats_res.get("integrity_verified", 0):
            failures.append("resume: nothing was digest-verified")
        if stats_res.get("integrity_mismatches", 0):
            failures.append("resume: clean snapshots reported mismatches")
        if rows != ref:
            failures.append("resume: digest-verified resume is not "
                            "bit-identical")

    os.environ.pop("RDFIND_INTEGRITY", None)
    if failures:
        for f in failures:
            print(f"integrity_parity: {f}", file=sys.stderr)
        return 1
    print(f"integrity_parity: OK — {len(ref)} CIND rows bit-identical with "
          f"the knob on/off, output digest {ref_digest}, one planted flip "
          "detected+repaired, 8 -> 2 resume digest-verified")
    return 0


if __name__ == "__main__":
    sys.exit(main())
