"""Rung-3 kernel parity gate: planes {8,4,2} x emit_pipeline {off,on}.

Tiny planted workload on the CPU proxy, every knob combination asserted
bit-identical — both for the packed containment kernel (interpreted Pallas
vs jnp planes, plus cross-combination output hashes) and for the dense
CIND sweep (fused and materialized discover_pairs_dense).  Off-TPU the
emit=1 rows exercise the probe-refusal fallback path, which is exactly the
contract under test: forcing a knob must never change results, only
schedules.  scripts/verify.sh runs this between the tier-1 suite and the
tiny bench; VERIFY_SKIP_KERNEL_RUNGS=1 opts out.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

PLANES = ("8", "4", "2")
EMITS = ("0", "1")


def main() -> int:
    import jax.numpy as jnp

    from rdfind_tpu.ops import cooc, sketch

    failures = []

    # --- Packed containment kernel: per-combo jnp parity + one shared hash.
    hashes = {}
    for pb in PLANES:
        for em in EMITS:
            cooc.PLANE_BITS, cooc.EMIT_PIPELINE = pb, em
            r = sketch.kernel_selfcheck(n_rows=128, n_bits=2048, repeats=1)
            tag = f"planes{pb}/emit{em}"
            if not r["parity"]:
                failures.append(f"{tag}: pallas vs jnp parity FAILED")
            hashes[tag] = r["out_hash"]
    if len(set(hashes.values())) != 1:
        failures.append(f"containment outputs differ across combos: {hashes}")

    # --- Dense CIND sweep: planted membership, fused x materialized x the
    # plane/emit grid, identical (dep, ref) pair sets everywhere.
    rng = np.random.default_rng(3)
    n_lines, num_caps = 300, 200
    plan = cooc.dense_plan(n_lines, num_caps)
    member = rng.random((plan.l_pad, plan.c_pad)) < 0.02
    # Plant real containments (dep col k subset of ref col 100+k): random
    # IID membership at this size admits none, and a gate that can only
    # ever compare empty sets proves nothing.
    for k in range(20):
        member[:, 100 + k] |= member[:, k]
    dt = jnp.int8 if plan.dtype == "int8" else jnp.bfloat16
    m = jax.block_until_ready(jnp.asarray(member, dt))
    dep_count = member.sum(axis=0).astype(np.int64)
    cap_id = rng.integers(0, 1 << 20, plan.c_pad).astype(np.int64)

    baseline = None
    for pb in PLANES:
        for em in EMITS:
            for fv in ("0", "1"):
                cooc.PLANE_BITS, cooc.EMIT_PIPELINE = pb, em
                cooc.FUSE_VERDICT = fv
                mode_plan = cooc.dense_plan(n_lines, num_caps)
                d, r, _ = cooc.discover_pairs_dense(
                    m, dep_count, cap_id, cap_id, cap_id, 3, num_caps,
                    mode_plan.tile, starts=mode_plan.dep_tile_starts,
                    plan=mode_plan)
                pairs = set(zip(d.tolist(), r.tolist()))
                tag = f"planes{pb}/emit{em}/fuse{fv}"
                if baseline is None:
                    baseline = pairs
                    if not pairs:
                        failures.append("planted workload produced 0 pairs "
                                        "(gate is vacuous)")
                elif pairs != baseline:
                    failures.append(
                        f"{tag}: dense pair set differs from baseline "
                        f"({len(pairs)} vs {len(baseline)} pairs)")

    if failures:
        for f in failures:
            print(f"kernel_rung_parity: {f}", file=sys.stderr)
        return 1
    print(f"kernel_rung_parity: OK — containment hash "
          f"{next(iter(hashes.values()))} and {len(baseline)} dense pairs "
          f"identical across {len(PLANES) * len(EMITS)} containment and "
          f"{len(PLANES) * len(EMITS) * 2} dense combos")
    return 0


if __name__ == "__main__":
    sys.exit(main())
