"""Live-console smoke test: a tiny run with --console-port 0, probed live.

scripts/verify.sh runs this after the tier-1 suite.  It launches a small
CPU rdfind run with an ephemeral console port, reads the bound port from
the child's stderr announcement, fetches /metrics and /progress WHILE the
run executes, and asserts both parse (Prometheus text exposition and the
progress JSON respectively).  A bind failure — some sandboxes forbid even
loopback listening — is a graceful skip (exit 0 with a SKIP line), not a
failure: the console is best-effort by design and the run must not depend
on it.

Exit codes: 0 ok/skip, 1 smoke failure.
"""

import json
import os
import re
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
URL_RE = re.compile(r"run console on (http://[0-9.]+:\d+)/")
# Prometheus text exposition: comments/blank lines, or `name{labels} value`.
SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$")


def write_dataset(path: str, n: int = 5_000) -> None:
    """Small synthetic .nt with enough shared objects to produce CINDs (and
    enough rows that the run outlives the two HTTP probes — ~40s of work,
    while the probes land within the first seconds; the CIND count on this
    shape grows superlinearly in n, so keep it small)."""
    with open(path, "w") as f:
        for i in range(n):
            f.write(f"<http://x/s{i % 997}> <http://x/p{i % 7}> "
                    f"<http://x/o{i % 83}> .\n")


def fetch(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="console_smoke_") as tmp:
        data = os.path.join(tmp, "smoke.nt")
        write_dataset(data)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        child = subprocess.Popen(
            [sys.executable, "-m", "rdfind_tpu.programs.rdfind", data,
             "--support", "2", "--traversal-strategy", "1",
             "--console-port", "0"],
            cwd=REPO, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        base = None
        stderr_tail = []
        deadline = time.time() + 120
        try:
            for line in child.stderr:
                stderr_tail.append(line.rstrip())
                if "could not bind" in line:
                    print("console smoke: SKIP (console could not bind a "
                          "loopback port in this environment)")
                    child.wait(timeout=300)
                    return 0
                m = URL_RE.search(line)
                if m:
                    base = m.group(1)
                    break
                if time.time() > deadline:
                    break
            if base is None:
                print("console smoke: FAIL — run exited without announcing "
                      "a console URL; stderr tail:")
                for ln in stderr_tail[-15:]:
                    print(f"  {ln}")
                child.kill()
                return 1

            prom = fetch(base + "/metrics").decode()
            bad = [ln for ln in prom.splitlines()
                   if ln and not ln.startswith("#")
                   and not SAMPLE_RE.match(ln)]
            if bad:
                print(f"console smoke: FAIL — /metrics lines do not parse "
                      f"as Prometheus text: {bad[:3]}")
                child.kill()
                return 1

            progress = json.loads(fetch(base + "/progress"))
            if "run_stage" not in progress:
                print(f"console smoke: FAIL — /progress lacks run_stage: "
                      f"{sorted(progress)}")
                child.kill()
                return 1
            print(f"console smoke: probed {base} mid-run "
                  f"(stage={progress.get('run_stage')}, "
                  f"{len(prom.splitlines())} metric lines)")
        except BaseException:
            child.kill()
            raise
        # Drain the rest of stderr (closing the pipe mid-run would EPIPE the
        # child's own diagnostics) and let the run finish.
        child.stderr.read()
        rc = child.wait(timeout=600)
        if rc != 0:
            print(f"console smoke: FAIL — run exited rc={rc}")
            return 1
        print("console smoke: ok")
        return 0


if __name__ == "__main__":
    sys.exit(main())
