"""Benchmark: incremental (--delta) discovery vs a from-scratch re-run.

Grows a planted-CIND workload (utils/synth.generate_planted_cinds — the
CIND-dense generator whose rules never interact, so join lines stay small
and uniform like a wide-schema dataset; the zipf generators' hub values
would let a single touched triple dirty a quarter of the evidence, which
benchmarks the fallback ladder, not incrementality) with 0.1% / 1% / 10%
insert+delete change batches (utils/synth.grow_delta_batches — half
recombinations, half brand-new values, so the dictionary tail and new
buckets are exercised).  For each batch size it measures end-to-end wall
of

  * full     — a from-scratch driver run over the updated dataset, and
  * delta    — the --delta replay of just the batch against a persisted
               base bundle (a fresh copy per size: a delta run advances
               its bundle's generation in place),

asserts the two tables are bit-identical (a speedup over a wrong answer is
worthless), and reports ``delta_speedup`` (full wall / delta wall) and
``frac_passes_rerun`` per size.  The paper's promise is cost proportional
to the change: speedup should fall and frac_passes_rerun rise as the batch
grows.

Prints ONE JSON line (bench.py shape) and appends a provenance-keyed row
to BENCH_HISTORY.jsonl for the regression sentinel.  The row's workload
stamp is distinct from bench.py's, so output digests never cross-compare.

Env: BENCH_DELTA_TRIPLES (default 8000, rounded to whole planted rules),
BENCH_DELTA_MIN_SUPPORT (10), BENCH_BACKEND=cpu pins the CPU proxy,
BENCH_HISTORY as in bench.py.
"""

import json
import os
import shutil
import sys
import tempfile
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from bench import _init_backend, _record_history  # noqa: E402
from rdfind_tpu.obs import integrity as obs_integrity  # noqa: E402
from rdfind_tpu.obs import sentinel as obs_sentinel  # noqa: E402

FRACS = ((0.001, "d01pct"), (0.01, "d1pct"), (0.1, "d10pct"))


def _timed_run(cfg_kwargs):
    from rdfind_tpu.runtime import driver
    t0 = time.perf_counter()
    res = driver.run(driver.Config(**cfg_kwargs))
    return res, time.perf_counter() - t0


def _run(n: int, min_support: int, backend: str) -> dict:
    from rdfind_tpu.utils import synth

    # One planted rule is ~(ref_size + support) * 4 + spoilers triples;
    # size the rule count to land near the requested n.
    support = min_support + 2
    ref_size = support + max(support // 4, 8)
    per_rule = (ref_size + support) * 4 + 4 * max(2, support // 8)
    n_rules = max(4, n // per_rule)
    triples, _expected = synth.generate_planted_cinds(n_rules, support)
    n = int(triples.shape[0])
    detail = {"backend": backend,
              "provenance": obs_sentinel.provenance(backend=backend),
              "n_triples": n, "n_rules": n_rules,
              "min_support": min_support}
    delta_detail = {}
    headline = None

    with tempfile.TemporaryDirectory() as root:
        base_nt = os.path.join(root, "base.nt")
        synth.write_nt(base_nt, triples)

        # One base run persists the bundle (warm-up for the jit cache too);
        # each batch size replays against its own copy.
        bundle0 = os.path.join(root, "bundle0")
        base_res, base_wall = _timed_run(dict(
            input_paths=[base_nt], min_support=min_support,
            traversal_strategy=0, delta_state=bundle0))
        detail["base_wall_s"] = round(base_wall, 3)
        detail["base_cinds"] = len(base_res.table)

        for frac, key in FRACS:
            ins, dels = synth.grow_delta_batches(triples, frac, seed=7)
            p_ins = os.path.join(root, f"{key}_ins.nt")
            p_del = os.path.join(root, f"{key}_del.nt")
            p_upd = os.path.join(root, f"{key}_upd.nt")
            synth.write_nt(p_ins, ins)
            synth.write_nt(p_del, dels)
            synth.write_nt(p_upd, synth.apply_delta(triples, ins, dels))
            bundle = os.path.join(root, f"bundle_{key}")
            shutil.copytree(bundle0, bundle)

            full_res, full_wall = _timed_run(dict(
                input_paths=[p_upd], min_support=min_support,
                traversal_strategy=0))
            delta_res, delta_wall = _timed_run(dict(
                input_paths=[p_ins], delete_paths=[p_del],
                min_support=min_support, traversal_strategy=0,
                delta_base=bundle))
            if obs_integrity.digest_table(full_res.table) != \
                    obs_integrity.digest_table(delta_res.table):
                raise AssertionError(
                    f"{key}: delta output is not bit-identical to "
                    "from-scratch — refusing to report a speedup")
            st = delta_res.counters.get("stat-delta", {})
            n_passes = max(int(st.get("n_passes", 0)), 1)
            row = {
                "frac": frac,
                "inserts": len(ins), "deletes": len(dels),
                "full_wall_s": round(full_wall, 3),
                "delta_wall_s": round(delta_wall, 3),
                "delta_speedup": round(full_wall / max(delta_wall, 1e-9),
                                       2),
                "path": st.get("path"),
                "passes_rerun": int(st.get("passes_rerun", 0)),
                "frac_passes_rerun": round(
                    int(st.get("passes_rerun", 0)) / n_passes, 4),
                "dirty_row_frac": st.get("dirty_row_frac"),
                "cinds": len(delta_res.table),
            }
            delta_detail[key] = row
            print(f"bench_delta: {key} ({frac:.1%}) full {full_wall:.2f}s "
                  f"vs delta {delta_wall:.2f}s = "
                  f"{row['delta_speedup']}x, "
                  f"{row['passes_rerun']}/{n_passes} passes re-run "
                  f"[{row['path']}]", file=sys.stderr, flush=True)
            if key == "d1pct":
                headline = row["delta_speedup"]
                # Digest + workload stamp for the sentinel's correctness
                # gate (distinct from bench.py's workload by construction).
                detail["output_digest"] = obs_integrity.digest_hex(
                    *obs_integrity.digest_table(delta_res.table))
                detail["workload"] = {"bench": "delta", "n_triples": n,
                                      "min_support": min_support,
                                      "frac": frac, "seed": 42}

    detail["delta"] = delta_detail
    return {
        "metric": "delta_speedup_1pct",
        "value": headline if headline is not None else 0,
        "unit": "x",
        "vs_baseline": headline if headline is not None else 0,
        "detail": detail,
    }


def main():
    n = int(os.environ.get("BENCH_DELTA_TRIPLES", 8_000))
    min_support = int(os.environ.get("BENCH_DELTA_MIN_SUPPORT", 10))
    try:
        backend = _init_backend()
        result = _run(n, min_support, backend)
    except Exception as e:
        tb = traceback.format_exc(limit=3)
        result = {
            "metric": "delta_speedup_1pct", "value": 0, "unit": "x",
            "vs_baseline": 0,
            "detail": {"error": f"{type(e).__name__}: {e}",
                       "traceback": tb.splitlines()[-3:]},
        }
    print(json.dumps(result, default=str))
    _record_history(result)


if __name__ == "__main__":
    main()
