"""Scale point: DBpedia-shaped synthetic multi-host run (BASELINE configs 3-4 proxy).

The real DBpedia-2016 dump cannot exist in this zero-egress image, so this
drives the SAME machinery (multi-host sharded ingest with hash-partitioned
interning + sharded discovery) over a synthetic dataset shaped like it:
power-law join lines, URI/literal mix, --support high (the reference's
config-3/4 settings use 1000).  Records wall-clock per phase, peak host RSS,
exchange overflow retries, and CIND counts — the measured row BASELINE.md
configs 3-4 cite.

Method: writes N tab-separated triples as 8 shard files, then launches 2
coordinated processes (4 fake CPU devices each; the minicluster analog with
real process boundaries) running the CLI with --sharded-ingest; parses each
worker's counter/phase report.  NB: this box has ONE CPU core — wall-clock
numbers measure the dataflow on an oversubscribed core, so the artifact's
honest headline is the MEMORY + correctness bound (per-host RSS vs dataset
size), with wall-clock reported as-is.

Run:  python bench_scale.py [--n 20000000] [--support 1000] [--strategies 0,1]
Output: one JSON line per (strategy) run -> append to SCALE_r05.jsonl.
RDFIND_PAIR_ROW_BUDGET bounds per-device pair buffers (dep-slice streaming).
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def write_shards(n: int, seed: int, out_dir: str, n_shards: int = 8):
    """Synthetic triples -> tab-separated shard files (streamed, bounded RAM)."""
    sys.path.insert(0, REPO)
    import numpy as np

    from rdfind_tpu.utils.synth import generate_dbpedia_shaped

    os.makedirs(out_dir, exist_ok=True)
    paths = [os.path.join(out_dir, f"shard{i}.tsv") for i in range(n_shards)]
    files = [open(p, "w", buffering=1 << 20) for p in paths]
    chunk = 1_000_000
    done = 0
    while done < n:
        m = min(chunk, n - done)
        # Independent chunks with distinct seeds: the union keeps the same
        # DBpedia-like shape while generation stays O(chunk) RAM.
        t = generate_dbpedia_shaped(m, seed=seed + done // chunk)
        shard_of = (np.arange(m) + done) % n_shards
        for i, f in enumerate(files):
            rows = t[shard_of == i]
            f.write("\n".join(
                f"v{a}\tv{b}\tv{c}" for a, b, c in rows))
            f.write("\n")
        done += m
        print(f"  wrote {done}/{n}", file=sys.stderr, flush=True)
    for f in files:
        f.close()
    return paths


def run_two_hosts(paths, support: int, strategy: int, extra=(),
                  timeout_s: int = 4 * 3600):
    port = _free_port()
    procs = []
    logs = []
    for pid in range(2):
        cmd = [sys.executable, "-m", "rdfind_tpu.programs.rdfind",
               *paths, "--tabs", "--support", str(support),
               "--traversal-strategy", str(strategy), "--use-fis",
               "--sharded-ingest", "--counters", "1",
               "--coordinator", f"127.0.0.1:{port}",
               "--num-hosts", "2", "--host-index", str(pid), *extra]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=4"
            " --xla_cpu_collective_timeout_seconds=7200"
            " --xla_cpu_collective_call_warn_stuck_timeout_seconds=600"
            " --xla_cpu_collective_call_terminate_timeout_seconds=7200")
        # Worker output goes to FILES, not pipes: with pipes drained
        # sequentially, a worker that fills its 64 KB stderr pipe (XLA
        # warnings) blocks mid-collective and deadlocks the pair.
        out_f = open(f"/tmp/bench_scale_w{pid}_s{strategy}.out", "w")
        err_f = open(f"/tmp/bench_scale_w{pid}_s{strategy}.err", "w")
        logs.append((out_f, err_f))
        procs.append(subprocess.Popen(
            cmd, cwd=REPO, stdout=out_f, stderr=err_f, text=True, env=env))
    try:
        for p in procs:
            p.wait(timeout=timeout_s)
    finally:
        # Never orphan multi-GB workers (a killed parent must not leave two
        # coordinated processes thrashing the box's one core).
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for out_f, err_f in logs:
            out_f.close()
            err_f.close()
    outs = []
    for pid in range(2):
        with open(f"/tmp/bench_scale_w{pid}_s{strategy}.out") as f:
            out = f.read()
        with open(f"/tmp/bench_scale_w{pid}_s{strategy}.err") as f:
            err = f.read()
        outs.append((out, err))
    return procs, outs


def parse_report(err: str) -> dict:
    counters, phases = {}, {}
    for line in err.splitlines():
        line = line.strip()
        if line.startswith("phase "):
            name, ms = line[6:].split(": ")
            phases[name] = float(ms.rstrip(" ms"))
        elif ": " in line and not line.startswith(("note", "warning")):
            k, _, v = line.partition(": ")
            if v.strip().lstrip("-").isdigit() and " " not in k:
                counters[k] = int(v)
    return {"counters": counters, "phases_ms": phases}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=20_000_000)
    ap.add_argument("--support", type=int, default=1000)
    ap.add_argument("--strategies", default="0,1")
    ap.add_argument("--seed", type=int, default=404)
    ap.add_argument("--data-dir", default="/tmp/rdfind_scale")
    ap.add_argument("--keep-data", action="store_true")
    args = ap.parse_args()

    t0 = time.perf_counter()
    marker = os.path.join(args.data_dir, f"ok_{args.n}_{args.seed}")
    if os.path.exists(marker):
        paths = [os.path.join(args.data_dir, f"shard{i}.tsv")
                 for i in range(8)]
        print(f"reusing shards in {args.data_dir}", file=sys.stderr)
    else:
        print(f"writing {args.n} triples...", file=sys.stderr)
        paths = write_shards(args.n, args.seed, args.data_dir)
        open(marker, "w").close()
    gen_s = time.perf_counter() - t0

    for strat in (int(s) for s in args.strategies.split(",")):
        t0 = time.perf_counter()
        procs, outs = run_two_hosts(paths, args.support, strat)
        wall = time.perf_counter() - t0
        row = {"n_triples": args.n, "support": args.support,
               "strategy": strat, "wall_s": round(wall, 1),
               "datagen_s": round(gen_s, 1), "hosts": 2,
               "box": "1 CPU core, 4 fake devices/host",
               "pair_row_budget": os.environ.get("RDFIND_PAIR_ROW_BUDGET")}
        for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
            rep = parse_report(err)
            row[f"host{pid}"] = {
                "rc": p.returncode,
                "peak_rss_mb": rep["counters"].get("peak-rss-mb"),
                **({"counters": rep["counters"],
                    "phases_ms": rep["phases_ms"]} if pid == 0 else {}),
            }
            if p.returncode != 0:
                row[f"host{pid}"]["stderr_tail"] = err[-1500:]
        print(json.dumps(row), flush=True)
        with open(os.path.join(REPO, "SCALE_r05.jsonl"), "a") as f:
            f.write(json.dumps(row) + "\n")

    if not args.keep_data:
        print(f"note: shards kept in {args.data_dir} (pass --keep-data to "
              f"silence; delete manually to reclaim disk)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
