"""Benchmark: the mmap'd CIND index's build, open, and query hot paths.

Plants a synthetic CIND workload (~BENCH_SERVE_CINDS dependencies ×
~8 referenced captures each over a fresh value dictionary — the serving
shape, no discovery run needed), writes it through
runtime/serving.write_index at two sizes a 10x spread apart, and measures:

  * open time at both sizes — ASSERTED flat (mmap + header parse only; a
    size-dependent open means something started materializing sections);
  * single-thread and multi-thread holds() QPS over a warm reader (hit/miss
    mix, string captures through the memo + id fast path);
  * per-query latency p50/p95/p99 for all three query types (holds,
    referenced, top-k).

It also measures the observability tax and the freshness plane: the same
query stream through the IndexService path with per-request telemetry off
then on (answers asserted bit-identical; the instrumented path must hold
>= 0.9x the bare QPS), and the bundle-commit -> serving-swap staleness
across a live gen-0 -> gen-1 hot swap.

Prints ONE JSON line (bench.py shape) and appends a provenance-keyed row
to BENCH_HISTORY.jsonl; `serve_qps` / `serve_open_ms` / `serve_p99_us` /
`serve_obs_qps` / `serve_obs_overhead_frac` / `serve_swap_staleness_s`
gate in obs/sentinel.METRIC_SPECS like kernel regressions.

Env: BENCH_SERVE_CINDS (default 10_000), BENCH_SERVE_QUERIES (default
50_000), BENCH_SERVE_THREADS (default 4), BENCH_SERVE_MIN_QPS (default
50_000; the single-thread holds() floor, 0 disables the assert),
BENCH_SERVE_OBS_MAX_FRAC (default 0.1; the instrumented-path overhead
ceiling, 0 disables the assert), BENCH_HISTORY as in bench.py.
"""

import json
import os
import sys
import tempfile
import threading
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from bench import _record_history  # noqa: E402
from rdfind_tpu import conditions as cc  # noqa: E402
from rdfind_tpu.data import NO_VALUE, CindTable  # noqa: E402
from rdfind_tpu.obs import sentinel as obs_sentinel  # noqa: E402
from rdfind_tpu.runtime import serving  # noqa: E402

REFS_PER_DEP = 8


def _planted(n_cinds: int, seed: int = 7):
    """(values, table): ~n_cinds CINDs over n_cinds//8 dependents, every
    capture a distinct value — the dictionary-heavy serving shape."""
    rng = np.random.default_rng(seed)
    n_deps = max(1, n_cinds // REFS_PER_DEP)
    dep_vals = [f"dep:{i:08d}" for i in range(n_deps)]
    ref_vals = [f"ref:{i:08d}" for i in range(n_deps * REFS_PER_DEP)]
    values = sorted(dep_vals + ref_vals)
    vid = {v: i for i, v in enumerate(values)}
    codes = cc.ALL_VALID_CAPTURE_CODES[:4]
    rows = []
    for d in range(n_deps):
        code_d = codes[d % len(codes)]
        support = int(rng.integers(2, 1000))
        for r in range(REFS_PER_DEP):
            rv = ref_vals[d * REFS_PER_DEP + r]
            rows.append((code_d, vid[dep_vals[d]], NO_VALUE,
                         codes[(d + r) % len(codes)], vid[rv], NO_VALUE,
                         support))
    return values, CindTable.from_rows(rows)


def _open_ms(path: str, repeats: int = 7) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        r = serving.IndexReader(path)
        best = min(best, (time.perf_counter() - t0) * 1e3)
        r.close()
    return best


def _percentiles(us: list) -> dict:
    a = np.asarray(us)
    return {"p50": round(float(np.percentile(a, 50)), 1),
            "p95": round(float(np.percentile(a, 95)), 1),
            "p99": round(float(np.percentile(a, 99)), 1)}


def _query_mix(reader, values, table, n_queries: int, seed: int = 11):
    """[(dep_capture, ref_capture), ...] string-capture pairs, ~2/3 hits
    and 1/3 misses (a present dependent against a foreign reference)."""
    rng = np.random.default_rng(seed)
    t = len(table)
    pick = rng.integers(0, t, n_queries)
    miss = rng.random(n_queries) < 1 / 3
    out = []
    for i, row in enumerate(pick):
        dep = (int(table.dep_code[row]), values[int(table.dep_v1[row])],
               None)
        j = int(rng.integers(0, t))
        ref_row = j if miss[i] else int(row)
        ref = (int(table.ref_code[ref_row]),
               values[int(table.ref_v1[ref_row])], None)
        out.append((dep, ref))
    return out


def _run(n_cinds: int, n_queries: int, n_threads: int,
         min_qps: float) -> dict:
    detail = {"provenance": obs_sentinel.provenance(backend="cpu"),
              "n_cinds_requested": n_cinds}
    serve = {}
    with tempfile.TemporaryDirectory() as root:
        small_dir = os.path.join(root, "small")
        big_dir = os.path.join(root, "big")
        values_s, table_s = _planted(max(REFS_PER_DEP, n_cinds // 10))
        values_b, table_b = _planted(n_cinds)

        t0 = time.perf_counter()
        serving.write_index(small_dir, values_s, table_s, generation=0,
                            output_digest="bench-small")
        p_big = None
        t1 = time.perf_counter()
        p_big = serving.write_index(big_dir, values_b, table_b,
                                    generation=0,
                                    output_digest="bench-big")
        build_ms = (time.perf_counter() - t1) * 1e3
        serve["build_small_ms"] = round((t1 - t0) * 1e3, 2)
        serve["build_ms"] = round(build_ms, 2)
        p_small = serving.index_path(small_dir)
        serve["index_bytes_small"] = os.path.getsize(p_small)
        serve["index_bytes"] = os.path.getsize(p_big)

        # Open must be O(header): flat across the 10x size spread.
        open_small = _open_ms(p_small)
        open_big = _open_ms(p_big)
        serve["open_ms_small"] = round(open_small, 3)
        serve["open_ms_big"] = round(open_big, 3)
        serve["open_ms"] = round(open_big, 3)
        assert open_big < open_small * 4 + 20.0, (
            f"index open is size-dependent: {open_small:.2f}ms at "
            f"{serve['index_bytes_small']}B vs {open_big:.2f}ms at "
            f"{serve['index_bytes']}B — mmap open must be O(header)")

        reader = serving.IndexReader(p_big)
        serve["n_cinds"] = reader.n_cinds
        serve["n_values"] = reader.n_values
        queries = _query_mix(reader, values_b, table_b, n_queries)
        holds = reader.holds
        for dep, ref in queries[:2000]:
            holds(dep, ref)  # warm the value/capture memo

        t0 = time.perf_counter()
        hits = 0
        for dep, ref in queries:
            if holds(dep, ref):
                hits += 1
        wall = time.perf_counter() - t0
        qps = n_queries / wall
        serve["holds_qps"] = round(qps, 1)
        serve["holds_hit_frac"] = round(hits / n_queries, 3)
        print(f"bench_serve: holds() {qps:,.0f} QPS single-thread "
              f"({n_queries} queries, {hits} hits)", file=sys.stderr,
              flush=True)
        if min_qps:
            assert qps >= min_qps, (
                f"holds() {qps:,.0f} QPS < the {min_qps:,.0f} floor "
                f"(BENCH_SERVE_MIN_QPS=0 disables)")

        # Multi-thread: shared reader, per-thread query slices.
        def worker(slice_, out, i):
            h = reader.holds
            for dep, ref in slice_:
                h(dep, ref)
            out[i] = True

        chunk = max(1, n_queries // n_threads)
        slices = [queries[i * chunk:(i + 1) * chunk]
                  for i in range(n_threads)]
        done = [False] * n_threads
        threads = [threading.Thread(target=worker, args=(s, done, i))
                   for i, s in enumerate(slices)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        mt_wall = time.perf_counter() - t0
        serve["holds_qps_mt"] = round(
            sum(len(s) for s in slices) / mt_wall, 1)
        serve["threads"] = n_threads

        # Per-query latency (timed individually; percentile over a sample).
        lat_n = min(5000, n_queries)
        for name, fn in (
                ("holds", lambda q: holds(q[0], q[1])),
                ("referenced", lambda q: reader.referenced(q[0], limit=16)),
                ("topk", lambda q: reader.topk(10, decode=False))):
            us = []
            for q in queries[:lat_n]:
                t0 = time.perf_counter()
                fn(q)
                us.append((time.perf_counter() - t0) * 1e6)
            p = _percentiles(us)
            serve[f"{name}_p50_us"] = p["p50"]
            serve[f"{name}_p95_us"] = p["p95"]
            serve[f"{name}_p99_us"] = p["p99"]
            print(f"bench_serve: {name} p50/p95/p99 = {p['p50']}/"
                  f"{p['p95']}/{p['p99']} us", file=sys.stderr, flush=True)
        reader.close()

        # Instrumented vs bare: the same queries through the SERVICE path
        # (slot pin + per-request telemetry) with obs off, then on.  The
        # answers must be bit-identical and the slowdown bounded — the
        # observability plane may not tax the query plane more than
        # BENCH_SERVE_OBS_MAX_FRAC (default 10%, i.e. instrumented must
        # hold >= 0.9x bare; 0 disables the assert).
        from rdfind_tpu.obs import servestats
        svc = serving.IndexService(big_dir, verify=False)
        svc.poll()
        obs_n = min(n_queries, 20_000)
        sub = queries[:obs_n]
        prev_obs = os.environ.get("RDFIND_SERVE_OBS")

        def svc_pass():
            qh = svc.query_holds
            answers = []
            t0 = time.perf_counter()
            for dep, ref in sub:
                answers.append(qh(dep, ref)["holds"])
            return len(sub) / (time.perf_counter() - t0), answers

        try:
            os.environ["RDFIND_SERVE_OBS"] = "0"
            servestats.configure()
            svc_pass()  # warm
            qps_bare, ans_bare = svc_pass()
            os.environ["RDFIND_SERVE_OBS"] = "1"
            servestats.reset()
            servestats.configure()
            qps_obs, ans_obs = svc_pass()
            agg = servestats.aggregate()
        finally:
            if prev_obs is None:
                os.environ.pop("RDFIND_SERVE_OBS", None)
            else:
                os.environ["RDFIND_SERVE_OBS"] = prev_obs
            servestats.reset()
            servestats.configure()
        svc.close()
        assert ans_bare == ans_obs, \
            "instrumentation changed query answers (must be bit-identical)"
        assert agg["requests"]["holds"]["ok"] == obs_n, (
            f"sharded stats lost requests: {agg['requests']} != {obs_n}")
        overhead = 1.0 - qps_obs / qps_bare
        serve["holds_qps_svc_bare"] = round(qps_bare, 1)
        serve["holds_qps_svc_obs"] = round(qps_obs, 1)
        serve["obs_overhead_frac"] = round(overhead, 4)
        print(f"bench_serve: service holds() {qps_bare:,.0f} QPS bare vs "
              f"{qps_obs:,.0f} instrumented (overhead "
              f"{overhead * 100:.1f}%)", file=sys.stderr, flush=True)
        max_frac = float(os.environ.get("BENCH_SERVE_OBS_MAX_FRAC", 0.1))
        if max_frac:
            assert overhead <= max_frac, (
                f"observability overhead {overhead * 100:.1f}% > "
                f"{max_frac * 100:.0f}% (instrumented serving must hold "
                f">= {1 - max_frac:.1f}x the bare-path QPS; "
                f"BENCH_SERVE_OBS_MAX_FRAC=0 disables)")

        # Freshness across a LIVE gen-0 -> gen-1 hot swap: the recorded
        # staleness is the bundle-commit -> serving-swap lag.
        swap_dir = os.path.join(root, "swap")
        serving.write_index(swap_dir, values_s, table_s, generation=0,
                            output_digest="bench-g0")
        svc2 = serving.IndexService(swap_dir, verify=False)
        assert svc2.poll()["action"] == "swapped"
        serving.write_index(swap_dir, values_s, table_s, generation=1,
                            output_digest="bench-g1",
                            base_output_digest="bench-g0")
        verdict = svc2.poll()
        assert verdict["action"] == "swapped", verdict
        fresh = svc2.freshness()
        svc2.close()
        assert fresh["generations_behind"] == 0, fresh
        serve["swap_staleness_s"] = fresh["staleness_s"]
        print(f"bench_serve: gen-0->1 swap staleness "
              f"{fresh['staleness_s']}s", file=sys.stderr, flush=True)

    detail["serve"] = serve
    detail["workload"] = {"bench": "serve", "n_cinds": serve["n_cinds"],
                          "refs_per_dep": REFS_PER_DEP, "seed": 7}
    return {
        "metric": "serve_holds_qps",
        "value": serve["holds_qps"],
        "unit": "queries/s",
        "vs_baseline": serve["holds_qps"],
        "detail": detail,
    }


def main():
    n_cinds = int(os.environ.get("BENCH_SERVE_CINDS", 10_000))
    n_queries = int(os.environ.get("BENCH_SERVE_QUERIES", 50_000))
    n_threads = int(os.environ.get("BENCH_SERVE_THREADS", 4))
    min_qps = float(os.environ.get("BENCH_SERVE_MIN_QPS", 50_000))
    try:
        result = _run(n_cinds, n_queries, n_threads, min_qps)
    except Exception as e:
        tb = traceback.format_exc(limit=3)
        result = {
            "metric": "serve_holds_qps", "value": 0, "unit": "queries/s",
            "vs_baseline": 0,
            "detail": {"error": f"{type(e).__name__}: {e}",
                       "traceback": tb.splitlines()[-3:]},
        }
    print(json.dumps(result, default=str))
    _record_history(result)


if __name__ == "__main__":
    main()
