"""Benchmark matrix: BASELINE.md configs 1-2 across traversal strategies.

Fills the BASELINE.md measurement table (the reference's always-reporting
measurement machinery, AbstractFlinkProgram.java:65-77,175-182): one row per
(config, strategy) with wall-clock, pairs/s/chip, and CIND counts.

  Config 1: LUBM-1-shaped synthetic (~100k triples), support >= 10.
            "Unary CINDs only" is reported as the 1/1-family slice of the
            full output (the pipeline has no unary-only mode, like the
            reference, which always mines all four families).
  Config 2: DBpedia-person-slice-shaped synthetic (~2M triples),
            unary+binary, support >= 100.

Usage: python bench_matrix.py [--configs 1,2] [--strategies 0,1,2,3]
                              [--dtypes int8,bf16] [--plane-bits 8,4,2]
                              [--emit 0,1] [--hier off,0,1]
Prints one JSON line per row, then a summary table on stderr.  --dtypes adds
one row per cooc membership dtype (int8 rides the doubled int8 MXU peak and
is exact via int32 accumulation; pass "auto" for the probe-resolved default).
--hier adds a pod-scale exchange axis: "off" (default) keeps the
single-device models; "0"/"1"/"auto" run the SHARDED pipeline with
RDFIND_HIER_EXCHANGE pinned to that value (on a single-host run a 2-host
pod is modeled via --hier-hosts so the ICI/DCN ledger split is
meaningful), and each such row records the exchange byte totals.

CIND-count note: strategies 0/2 emit every CIND; small-to-large (1) and
late-BB (3) emit their raw forms, whose 2/1 and 2/2 families omit
1/x-implied members by construction (the reference's behavior for both) —
so their totals are lower while the 1/1 and 1/2 families match exactly.
"""

import argparse
import json
import os
import sys
import time


CONFIGS = {
    1: dict(n=100_000, min_support=10, seed=101,
            synth=dict(n_predicates=18, n_entities=17_000),
            label="LUBM-1-shaped 100k, support>=10"),
    2: dict(n=2_000_000, min_support=100, seed=202,
            # IID synthetic data cannot sustain exact containment at support
            # 100 (both first cuts found zero CINDs at 2M), so config 2 gets
            # the structural-inclusion overlay real RDF has — see
            # utils/synth.inject_cind_structure.
            synth=dict(n_predicates=64, n_entities=60_000),
            structured=True,
            label="person-slice-shaped 2M, unary+binary, support>=100"),
}


def run_one(config_id: int, strategy: int, dtype: str = "auto",
            plane_bits: str = "auto", fuse: str = "auto",
            emit: str = "auto", hier: str = "off",
            hier_hosts: int = 2) -> dict:
    from rdfind_tpu.models import (allatonce, approximate, late_bb,
                                   small_to_large)
    from rdfind_tpu.ops import cooc
    from rdfind_tpu.utils.synth import generate_triples

    spec = CONFIGS[config_id]
    triples = generate_triples(spec["n"], seed=spec["seed"], **spec["synth"])
    if spec.get("structured"):
        from rdfind_tpu.utils.synth import inject_cind_structure
        triples = inject_cind_structure(triples)

    if dtype not in ("auto", "bf16", "int8"):
        raise ValueError(f"dtype must be auto, bf16 or int8, got {dtype!r}")
    if plane_bits not in ("auto", "2", "4", "8"):
        raise ValueError(f"plane bits must be auto, 2, 4 or 8, "
                         f"got {plane_bits!r}")
    if fuse not in ("auto", "0", "1"):
        raise ValueError(f"fuse must be auto, 0 or 1, got {fuse!r}")
    if emit not in ("auto", "0", "1"):
        raise ValueError(f"emit must be auto, 0 or 1, got {emit!r}")
    if hier not in ("off", "0", "1", "auto"):
        raise ValueError(f"hier must be off, 0, 1 or auto, got {hier!r}")

    hier_extra = {}
    if hier == "off":
        discover = {0: allatonce.discover, 1: small_to_large.discover,
                    2: approximate.discover, 3: late_bb.discover}[strategy]
        run = lambda stats: discover(triples, spec["min_support"],  # noqa: E731
                                     stats=stats)
    else:
        # Pod-scale axis: the sharded pipeline with the two-level exchange
        # pinned to this row's knob (flat vs hierarchical over the same
        # mesh).  env is the knob's contract, saved/restored below.
        from rdfind_tpu.models import sharded
        from rdfind_tpu.parallel import mesh as mesh_mod
        sharded_fn = {0: sharded.discover_sharded,
                      1: sharded.discover_sharded_s2l,
                      2: sharded.discover_sharded_approx,
                      3: sharded.discover_sharded_late_bb}[strategy]
        mesh = mesh_mod.make_mesh()
        run = lambda stats: sharded_fn(triples, spec["min_support"],  # noqa: E731
                                       mesh=mesh, use_fis=True, stats=stats)

    saved = (cooc.COOC_DTYPE, cooc.PLANE_BITS, cooc.FUSE_VERDICT,
             cooc.EMIT_PIPELINE)
    saved_env = {k: os.environ.get(k)
                 for k in ("RDFIND_HIER_EXCHANGE", "RDFIND_HIER_HOSTS")}
    (cooc.COOC_DTYPE, cooc.PLANE_BITS, cooc.FUSE_VERDICT,
     cooc.EMIT_PIPELINE) = (dtype, plane_bits, fuse, emit)
    try:
        if hier != "off":
            os.environ["RDFIND_HIER_EXCHANGE"] = hier
            num_dev = int(mesh.devices.size)
            if (mesh_mod.topology_hosts(num_dev) == 1
                    and num_dev % hier_hosts == 0):
                os.environ["RDFIND_HIER_HOSTS"] = str(hier_hosts)
        stats: dict = {}
        run(stats)  # warm (compile)
        stats = {}
        t0 = time.perf_counter()
        table = run(stats)
        wall = time.perf_counter() - t0
        if hier != "off":
            sites = stats.get("exchange_sites", {})
            hier_extra = {
                "hier": hier,
                "hosts": mesh_mod.topology_hosts(int(mesh.devices.size)),
                "exchange_bytes": sum(e["bytes"] for e in sites.values()),
                "ici_bytes": sum(e["ici_bytes"] for e in sites.values()),
                "dcn_bytes": sum(e["dcn_bytes"] for e in sites.values()),
                "overlap_efficiency": (stats.get("overlap")
                                       or {}).get("overlap_efficiency"),
            }
    finally:
        (cooc.COOC_DTYPE, cooc.PLANE_BITS, cooc.FUSE_VERDICT,
         cooc.EMIT_PIPELINE) = saved
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    total_pairs = int(stats.get("total_pairs", 0))
    return {
        **hier_extra,
        "config": config_id,
        "label": spec["label"],
        "strategy": strategy,
        "cooc_dtype": stats.get("cooc_dtype", dtype),
        "plane_bits": stats.get("plane_bits"),
        "fuse_verdict": fuse,
        # The full knob->decision struct (probes included): one glance says
        # what kernel actually ran in this cell.
        "kernel_resolution": stats.get("kernel_resolution"),
        "n_blocks_skipped": stats.get("n_blocks_skipped"),
        "dense_plan": stats.get("dense_plan"),
        "wall_s": round(wall, 3),
        "total_pairs": total_pairs,
        "pairs_per_sec_per_chip": round(total_pairs / wall, 1) if wall else 0,
        "cinds": len(table),
        "cind_families": table.family_counts(),
        "n_triples": int(len(triples)),
        "min_support": spec["min_support"],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="1,2")
    ap.add_argument("--strategies", default="0,1,2,3")
    ap.add_argument("--dtypes", default="int8,bf16",
                    help="cooc membership dtypes, one row each "
                         "(int8 | bf16 | auto)")
    ap.add_argument("--plane-bits", default="auto",
                    help="containment-kernel plane widths, one row each "
                         "(8 | 4 | 2 | auto; 4 = nibble planes, 2 = crumb "
                         "planes, each engaging natively only where the "
                         "matching MXU probe lowers)")
    ap.add_argument("--fuse", default="auto",
                    help="fused-verdict modes, one row each (0 | 1 | auto)")
    ap.add_argument("--emit", default="auto",
                    help="emit_pipeline K-loop modes for the packed "
                         "containment kernel, one row each (0 | 1 | auto; "
                         "falls back byte-identically off TPU)")
    ap.add_argument("--hier", default="off",
                    help="pod-scale exchange modes, one row each (off = "
                         "single-device models; 0 | 1 | auto = sharded "
                         "pipeline with RDFIND_HIER_EXCHANGE pinned)")
    ap.add_argument("--hier-hosts", type=int, default=2,
                    help="host count modeled on single-host runs for the "
                         "--hier rows' ICI/DCN attribution")
    args = ap.parse_args()

    # The axon tunnel can wedge (block inside a C call); use bench.py's
    # killable-subprocess probe + CPU fallback so the matrix always reports.
    from bench import _init_backend
    from rdfind_tpu.obs import sentinel as obs_sentinel
    backend = _init_backend()
    print(f"backend: {backend}", file=sys.stderr)
    # Shared row identity (git sha, core count, knob set): resolved once —
    # run_one's own env overrides are per-cell parameters already recorded in
    # the row, not ambient provenance.
    prov = obs_sentinel.provenance(backend=backend)

    rows = []
    for cid in (int(c) for c in args.configs.split(",")):
        for strat in (int(s) for s in args.strategies.split(",")):
            for dtype in args.dtypes.split(","):
                for pb in args.plane_bits.split(","):
                    for fuse in args.fuse.split(","):
                        for emit in args.emit.split(","):
                            for hier in args.hier.split(","):
                                try:
                                    row = run_one(cid, strat,
                                                  dtype=dtype.strip(),
                                                  plane_bits=pb.strip(),
                                                  fuse=fuse.strip(),
                                                  emit=emit.strip(),
                                                  hier=hier.strip(),
                                                  hier_hosts=args.hier_hosts)
                                except Exception as e:  # keep reporting
                                    row = {"config": cid, "strategy": strat,
                                           "cooc_dtype": dtype.strip(),
                                           "plane_bits": pb.strip(),
                                           "fuse_verdict": fuse.strip(),
                                           "emit_pipeline": emit.strip(),
                                           "hier": hier.strip(),
                                           "error":
                                               f"{type(e).__name__}: {e}"}
                                row["backend"] = backend
                                row["provenance"] = prov
                                rows.append(row)
                                print(json.dumps(row), flush=True)

    print(f"{'cfg':>3} {'strat':>5} {'dtype':>5} {'wall_s':>9} "
          f"{'Mpairs/s':>9} {'cinds':>8}", file=sys.stderr)
    for r in rows:
        if "error" in r:
            print(f"{r['config']:>3} {r['strategy']:>5} "
                  f"{r.get('cooc_dtype', '?'):>5} ERROR {r['error']}",
                  file=sys.stderr)
        else:
            print(f"{r['config']:>3} {r['strategy']:>5} "
                  f"{r['cooc_dtype']:>5} {r['wall_s']:>9.2f} "
                  f"{r['pairs_per_sec_per_chip'] / 1e6:>9.2f} "
                  f"{r['cinds']:>8}", file=sys.stderr)


if __name__ == "__main__":
    main()
