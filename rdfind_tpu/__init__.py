"""tpu-cind: a TPU-native framework for Conditional Inclusion Dependency discovery in RDF.

Re-implements the capabilities of stratosphere/rdfind (SIGMOD 2016) from scratch on
JAX/XLA/Pallas.  See SURVEY.md at the repo root for the structural analysis of the
reference that this build follows.

Package layout:
  conditions   -- the 6-bit capture-code algebra (reference: util/ConditionCodes.scala)
  data         -- table dataclasses (triples, captures, CINDs)
  dictionary   -- host-side string interning (replaces hash-dictionary compression)
  io/          -- N-Triples/N-Quads parsing, multi-file gz-aware reading, prefixes
  ops/         -- device primitives: segments, hashing, pair emission, sketches
  parallel/    -- mesh + collective bucket-exchange layer (shard_map/all_to_all)
  models/      -- the four traversal strategies (all-at-once, small-to-large, approx)
  runtime/     -- end-to-end drivers, CLI parameter surface
  utils/       -- host-side helpers (sorted-set algebra, trie)
"""

__version__ = "0.1.0"


def discover(triples, min_support: int = 10, strategy: int = 1, **kwargs):
    """One-call CIND discovery over an (N, 3) int32 id-triple table.

    ``strategy`` follows the reference's ids (RDFind.scala:50-56):
    0 = all-at-once, 1 = small-to-large (default), 2 = approximate
    all-at-once, 3 = late-BB.  Extra kwargs go to the strategy (e.g.
    ``projections=``, ``stats=``, ``clean_implied=``).  Returns a
    ``data.CindTable``.  For file ingest, CLI flags, checkpointing, and
    multi-device meshes use ``runtime.driver.run`` / the ``programs.rdfind``
    CLI.
    """
    from .runtime.driver import STRATEGIES

    fn = STRATEGIES.get(strategy)
    if fn is None:
        raise ValueError(f"unknown traversal strategy {strategy}; "
                         f"expected one of {sorted(STRATEGIES)}")
    return fn(triples, min_support, **kwargs)
