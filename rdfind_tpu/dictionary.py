"""Host-side string interning: the global value dictionary.

The reference compresses long values with a hash dictionary and escapes collisions
(operators/CreateHashes.scala, util/HashCollisionHandler.scala:11-42, plus the
CheckHashCollisions oracle program).  The TPU build instead interns every string
exactly once into dense int32 ids — exact (no collision handling needed, subsuming
CreateHashes/CombineHashes/ConditionCompressor/ConditionDecompressor) and the natural
device representation: all downstream compute is on int32 tables.

One dictionary spans all three triple fields, because join lines group captures by
shared *value* across fields (RDFind.scala:332-346 groups JoinCandidates by the raw
string join value).
"""

from __future__ import annotations

import dataclasses
import zlib

import numpy as np


def value_shard(value, num_shards: int) -> int:
    """THE hash-partition function for sharded interning.

    Every layer that splits a dictionary by value must agree on one partition
    or merged ids diverge: the multi-host hash-partitioned interning
    (runtime/multihost_ingest._value_owner), and the native parallel ingest's
    per-thread interner merge (native/rdfind_native.cpp, crc32 % S) both use
    exactly this: crc32 over the UTF-8 bytes, mod the shard count.
    """
    return zlib.crc32(str(value).encode("utf-8")) % num_shards


@dataclasses.dataclass
class Dictionary:
    """Sorted unique values; id = rank in sorted order."""

    values: np.ndarray  # sorted 1-D array of str/bytes

    def __len__(self) -> int:
        return len(self.values)

    def value(self, idx: int):
        return self.values[idx]

    def id(self, value) -> int:
        """Exact lookup; raises KeyError for unknown values."""
        pos = int(np.searchsorted(self.values, value))
        if pos >= len(self.values) or self.values[pos] != value:
            raise KeyError(value)
        return pos

    def ids(self, values) -> np.ndarray:
        values = np.asarray(values)
        pos = np.searchsorted(self.values, values)
        pos_clip = np.minimum(pos, len(self.values) - 1)
        if not np.all(self.values[pos_clip] == values):
            raise KeyError("unknown value(s) in lookup")
        return pos_clip.astype(np.int32)


def intern_triples(triples) -> tuple[np.ndarray, Dictionary]:
    """Intern an iterable/array of (s, p, o) values into an (N, 3) int32 id table."""
    arr = np.asarray(triples)
    if arr.size == 0:
        return np.zeros((0, 3), np.int32), Dictionary(np.zeros(0, object))
    if arr.ndim != 2 or arr.shape[1] != 3:
        raise ValueError(f"expected (N, 3) triples, got shape {arr.shape}")
    uniques, inverse = np.unique(arr.reshape(-1), return_inverse=True)
    if len(uniques) >= np.iinfo(np.int32).max:
        raise ValueError("dictionary exceeds int32 id space")
    ids = inverse.reshape(arr.shape).astype(np.int32)
    return ids, Dictionary(uniques)
