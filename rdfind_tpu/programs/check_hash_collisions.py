"""CheckHashCollisions: measure hash collision rate over all values.

The reference (programs/CheckHashCollisions.scala:59-67) validated its
hash-dictionary-compression assumption by 32-bit-hashing every distinct *string*
value and counting collisions.  Same here, with a CRC32-based string hash (the TPU
build's interning is exact, so this is purely a data-statistics oracle — e.g. for
deciding whether a hash-compressed ingest path would be safe).
"""

from __future__ import annotations

import argparse
import sys
import zlib

import numpy as np

from ..dictionary import intern_triples
from ..io import ntriples, reader


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="check-hash-collisions")
    p.add_argument("inputs", nargs="+")
    args = p.parse_args(argv)
    paths = reader.resolve_path_patterns(args.inputs)
    is_nq = paths[0].endswith((".nq", ".nq.gz"))
    triples = [t for _, line in reader.iter_lines(paths)
               if (t := ntriples.parse_line(line, expect_quad=is_nq)) is not None]
    _, dictionary = intern_triples(np.asarray(triples, dtype=object))
    hashes = np.fromiter(
        (zlib.crc32(str(v).encode("utf-8")) for v in dictionary.values),
        dtype=np.uint32, count=len(dictionary))
    n = len(dictionary)
    n_distinct_hashes = len(np.unique(hashes))
    print(f"Values: {n}")
    print(f"Distinct 32-bit hashes: {n_distinct_hashes}")
    print(f"Colliding values: {n - n_distinct_hashes} "
          f"({100.0 * (n - n_distinct_hashes) / max(n, 1):.4f}%)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
