"""CountDistinctValues: distinct URLs vs literals (programs/CountDistinctValues.scala:112-119)."""

from __future__ import annotations

import argparse
import sys

from ..io import ntriples, reader


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="count-distinct-values")
    p.add_argument("inputs", nargs="+")
    args = p.parse_args(argv)
    paths = reader.resolve_path_patterns(args.inputs)
    is_nq = paths[0].endswith((".nq", ".nq.gz"))
    urls, literals = set(), set()
    for _, line in reader.iter_lines(paths):
        t = ntriples.parse_line(line, expect_quad=is_nq)
        if t is None:
            continue
        for v in t:
            (urls if v.startswith("<") else literals).add(v)
    print(f"Distinct URLs: {len(urls)}")
    print(f"Distinct literals: {len(literals)}")
    print(f"Distinct values: {len(urls) + len(literals)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
