"""The CIND serving process: ``python -m rdfind_tpu.programs.serve DIR``.

Long-lived query server over the mmap'd index a discovery run committed
into DIR (``--delta-state`` bundles write one per generation; any run does
with ``RDFIND_SERVE_INDEX``).  The process:

  * opens the index zero-copy (runtime/serving.IndexReader — O(header));
  * serves the loopback console grown into the query plane
    (/query/holds, /query/referenced, /query/topk, plus /status with the
    index generation, integrity verdict, and certificate chain) and its
    admin plane (/metrics with the sharded per-request stats, /slo with
    the named SLO verdict, /debug/slowlog with the slow-query ring);
  * polls DIR (RDFIND_SERVE_POLL_S) and hot-swaps generations: when a
    delta run commits N+1 the new mapping is digest-verified and
    chain-checked, then atomically swapped in with zero dropped queries;
  * beats ``mode="serve"`` heartbeats into --obs carrying the freshness
    plane (index_age_s / staleness_s / generations_behind) and the SLO
    verdict, so tpu_watch sees generation/pending-swap/SLO state and
    heartbeat.assess never wedge-flags an idle server;
  * dumps the slow-query ring to --obs on SIGTERM and clean exit
    (slowlog-host<N>.json — the flightrec idiom).

Pure host-side stdlib+numpy: no JAX, no devices — a serving box needs
neither.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rdfind-serve",
        description="Serve CIND queries from a discovery run's mmap'd "
                    "index, hot-swapping delta generations as they commit.")
    p.add_argument("index_dir",
                   help="directory holding cind_index.bin (a --delta-state "
                        "bundle dir, or an RDFIND_SERVE_INDEX target)")
    p.add_argument("--console-port", type=int, default=None, metavar="PORT",
                   help="query-plane port (loopback HTTP; 0 = ephemeral, "
                        "printed to stderr; default RDFIND_CONSOLE_PORT "
                        "or 0)")
    p.add_argument("--obs", default=None, metavar="DIR",
                   help="heartbeat directory (mode=\"serve\" beats with the "
                        "loaded + on-disk generations; tpu_watch --status "
                        "reads it)")
    p.add_argument("--poll-s", type=float, default=None,
                   help="bundle-dir poll period in seconds (default "
                        "RDFIND_SERVE_POLL_S or 2.0)")
    p.add_argument("--max-s", type=float, default=0.0,
                   help="exit cleanly after this many seconds (0 = serve "
                        "forever; tests and parity gates use this)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from ..obs import console, heartbeat, servestats
    from ..runtime import serving

    servestats.configure()
    poll = serving.poll_s() if args.poll_s is None else max(0.05,
                                                            args.poll_s)
    svc = serving.IndexService(args.index_dir)
    first = svc.poll()
    if first["action"] == "swapped":
        print(f"rdfind-serve: generation {svc.generation} loaded from "
              f"{args.index_dir}", file=sys.stderr)
    else:
        # No (usable) index yet is not fatal: serve 503s and keep polling —
        # the producer run may still be committing.
        print(f"rdfind-serve: no usable index in {args.index_dir} yet "
              f"({first}); polling every {poll}s", file=sys.stderr)

    bind = args.console_port
    if bind is None:
        bind = console.env_port()
    if bind is None:
        bind = 0
    console.set_query_service(svc)
    port = console.start(bind)
    if port is None:
        # A console that cannot bind must never fail the server: heartbeats
        # still publish generation state for the watcher.
        print(f"rdfind-serve: console bind failed (port {bind}); "
              f"serving heartbeat-only", file=sys.stderr)
    else:
        print(f"rdfind-serve: console on http://127.0.0.1:{port} "
              f"(/query/holds /query/referenced /query/topk /status)",
              file=sys.stderr)

    def beat(final: bool = False) -> None:
        # The SLO engine evaluates on every beat even without --obs: the
        # loop IS its snapshot cadence for the burn-rate windows.
        fresh = svc.freshness()
        slo = servestats.evaluate_slo(fresh)
        if not args.obs:
            return
        os.makedirs(args.obs, exist_ok=True)
        st = svc.status()
        heartbeat.Heartbeat(args.obs).beat({
            "stage": "serve", "mode": "serve",
            "generation": st["generation"],
            "bundle_generation": st["bundle_generation"],
            "pending_swap": st["pending"],
            "index_stale": st["stale"], "swaps": st["swaps"],
            "index_age_s": fresh["index_age_s"],
            "staleness_s": fresh["staleness_s"],
            "generations_behind": fresh["generations_behind"],
            "slo": {"state": slo["state"], "slo": slo["slo"]},
            "console_port": port}, final=final)

    def _on_term(signum, frame):
        # Dump the slow-query ring before dying; SystemExit unwinds into
        # the finally block (final beat, console stop, service close).
        servestats.dump_slowlog(args.obs or ".", reason=f"signal-{signum}")
        raise SystemExit(128 + signum)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # not the main thread, or an exotic platform: skip the hook

    beat()
    t0 = time.monotonic()
    try:
        while True:
            if args.max_s and time.monotonic() - t0 >= args.max_s:
                break
            time.sleep(min(poll, 0.2) if args.max_s else poll)
            verdict = svc.poll()
            if verdict["action"] == "swapped":
                print(f"rdfind-serve: swapped to generation "
                      f"{verdict['generation']}", file=sys.stderr)
            elif verdict["action"] == "refused":
                print(f"rdfind-serve: swap refused: {verdict}",
                      file=sys.stderr)
            beat()
    except KeyboardInterrupt:
        pass
    finally:
        beat(final=True)
        if args.obs:
            servestats.dump_slowlog(args.obs, reason="exit")
        console.stop()
        svc.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
