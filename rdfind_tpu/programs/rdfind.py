"""The RDFind CLI: discover CINDs in RDF datasets on TPU.

Flag surface mirrors the reference's Parameters (programs/RDFind.scala:639-721).
Flags whose machinery is built-in or obsolete here are accepted for compatibility
and noted in help:
  --find-frequent-captures  exact capture-support pruning is always on;
  --hash-dictionary/--apply-hash/--hash-*  subsumed by exact string interning;
  --no-bulk-merge/--no-combinable-join  merge is always combiner-style.

Real behavior flags beyond the basics:
  --explicit-threshold/--sbf-bytes select and tune the half-approximate 1/1
      overlap round of the default strategy (models/small_to_large.py), as in
      the reference (SmallToLargeTraversalStrategy.scala:322-326);
  --balanced-overlap-candidates halves the 1/1 emission via rotation ownership
      (the reference's ring-distance relation, AbstractExtractBalancedUnary
      UnaryOverlapCandidates.scala:64-120).
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rdfind-tpu",
        description="Discover Conditional Inclusion Dependencies in RDF datasets "
                    "(TPU-native rebuild of stratosphere/rdfind).")
    p.add_argument("inputs", nargs="*",
                   help="input .nt/.nq[.gz] files or globs (for --delta "
                        "runs these are the INSERT batch; may be empty for "
                        "a delete-only batch)")
    p.add_argument("--delta", default=None, metavar="BASE_DIR",
                   dest="delta_base",
                   help="incremental run: apply the insert (positional) and "
                        "--deletes batches against the base bundle in "
                        "BASE_DIR (written by a full run with "
                        "--delta-state); output is bit-identical to a "
                        "from-scratch run on the updated dataset, and the "
                        "bundle advances one generation in place")
    p.add_argument("--delta-state", default=None, metavar="DIR",
                   help="full run: persist the delta base bundle (interned "
                        "dictionary + per-bucket join-line rows + per-pass "
                        "digests + the definitional CIND set) into DIR for "
                        "later --delta runs")
    p.add_argument("--deletes", nargs="*", default=[], metavar="FILE",
                   help="delete batch files for a --delta run (same formats "
                        "as the inputs; each line retracts one matching "
                        "triple)")
    p.add_argument("--prefixes", nargs="*", default=[],
                   help="nt-prefix files for URL shortening")
    p.add_argument("--support", type=int, default=10,
                   help="minimum support for CINDs (default 10)")
    p.add_argument("--traversal-strategy", type=int, default=1,
                   help="0=all-at-once 1=small-to-large 2=approx 3=late-bb")
    p.add_argument("--projection", default="spo",
                   help="fields to project captures on (subset of 'spo')")
    p.add_argument("--use-fis", action="store_true",
                   help="mine + use frequent item sets for pruning")
    p.add_argument("--use-ars", action="store_true",
                   help="mine + use association rules")
    p.add_argument("--clean-implied", action="store_true",
                   help="remove implied CINDs (minimality cleanup)")
    p.add_argument("--distinct-triples", action="store_true")
    p.add_argument("--asciify-triples", action="store_true")
    p.add_argument("--tabs", action="store_true", help="tab-separated input")
    p.add_argument("--only-read", action="store_true")
    p.add_argument("--do-only-join", action="store_true", dest="only_join")
    p.add_argument("--output", default=None, help="CIND output file")
    p.add_argument("--ar-output", default=None, help="association-rule output file")
    p.add_argument("--collect-result", action="store_true",
                   help="print CINDs to stdout")
    p.add_argument("--collector", default=None, metavar="HOST:PORT",
                   help="stream CINDs to a remote collector (JSON lines over "
                        "TCP; the reference's RMI result channel)")
    p.add_argument("--debug-level", type=int, default=0,
                   help="1: phase timings; 2: + sanity checks (trivial-CIND "
                        "count); 3: + print every CIND")
    p.add_argument("--print-plan", action="store_true",
                   help="dump the logical plan as JSON before executing")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="write an XLA profiler trace of the run (per-op "
                        "device timings; open with TensorBoard)")
    p.add_argument("--trace", default=None, metavar="DIR", dest="trace_dir",
                   help="obs: record host span traces (run/stage/pass/"
                        "dispatch/pull/exchange/checkpoint) + a heartbeat "
                        "file into DIR; a merged Chrome-trace JSON "
                        "(DIR/trace.json, per-host lanes) is written at run "
                        "end — open in Perfetto (ui.perfetto.dev).  Pairs "
                        "with --profile-dir: host spans emit matching "
                        "jax.profiler.TraceAnnotations")
    p.add_argument("--metrics-file", default=None, metavar="FILE",
                   help="obs: write the metrics registry as Prometheus text "
                        "exposition to FILE (atomically refreshed at every "
                        "stage boundary and at run end)")
    p.add_argument("--console-port", type=int, default=None, metavar="PORT",
                   help="obs: serve the live run console on this port "
                        "(loopback HTTP: /metrics /status /progress "
                        "/datastats /flightrec; 0 binds an ephemeral port, "
                        "printed to stderr; RDFIND_CONSOLE_PORT is the env "
                        "form)")
    p.add_argument("--counters", type=int, default=0, dest="counter_level")
    p.add_argument("--dop", type=int, default=1,
                   help="degree of parallelism = number of devices in the mesh")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="multi-host run: process 0's coordinator address "
                        "(every host runs the same command with its own "
                        "--host-index)")
    p.add_argument("--num-hosts", type=int, default=1,
                   help="multi-host run: total number of host processes")
    p.add_argument("--host-index", type=int, default=0,
                   help="multi-host run: this process's index in [0, "
                        "num-hosts)")
    # Accepted-for-compatibility (behavior built-in or subsumed; a note is
    # printed when set so no flag is a *silent* no-op):
    for flag in ("--find-frequent-captures", "--no-bulk-merge",
                 "--rebalance-join", "--apply-hash",
                 "--hash-dictionary", "--only-read-compat",
                 "--any-binary-captures"):
        p.add_argument(flag, action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--no-combinable-join", action="store_true",
                   help="plan ablation: ship raw join candidates instead of "
                        "combiner-deduped ones (sharded runs; same output)")
    p.add_argument("--balanced-overlap-candidates", action="store_true",
                   dest="balanced_11",
                   help="halve the 1/1 overlap emission via pair ownership "
                        "(strategy 1, single-device chunked backend; sharded "
                        "runs split emission via giant-line slicing instead)")
    p.add_argument("--rebalance-strategy", type=int, default=1,
                   choices=(1, 2),
                   help="split-line dependent ownership: 1 = hash-slice, "
                        "2 = contiguous range-slice (sharded runs)")
    p.add_argument("--rebalance-max-load", type=float, default=10000.0 * 10000,
                   help="absolute quadratic load above which a join line "
                        "always splits across devices (sharded runs)")
    p.add_argument("--merge-window-size", type=int, default=-1,
                   help="pair-merge window: max pairs materialized per chunk "
                        "in the chunked backend (-1 = auto)")
    p.add_argument("--create-join-histogram", action="store_true",
                   help="print a join-line size histogram "
                        "('Join size N encountered Mx')")
    p.add_argument("--find-only-fcs", type=int, default=0,
                   help="1: stop after frequent-condition mining, reporting "
                        "unary counts; 2: also mine binary (double) "
                        "conditions and association rules")
    for flag, dv in (("--rebalance-split", 1), ("--hash-bytes", -1),
                     ("--frequent-condition-strategy", 0)):
        p.add_argument(flag, type=int, default=dv, help=argparse.SUPPRESS)
    p.add_argument("--explicit-threshold", type=int, default=-1,
                   help="half-approximate 1/1 round: max exact per-dependent "
                        "counters (strategy 1, single-device chunked backend "
                        "only; -1 = exact overlaps).  Sharded runs bound 1/1 "
                        "memory via planned capacities + dep-slice streaming "
                        "(RDFIND_PAIR_ROW_BUDGET); their distributed "
                        "two-round count-min cut is "
                        "RDFIND_SHARDED_HALF_APPROX=1 (bit-identical "
                        "output)")
    p.add_argument("--sbf-bytes", type=int, default=-1, dest="sbf_bits",
                   help="bits per spectral (count-min) counter for the "
                        "half-approximate round (-1 = sized to support)")
    p.add_argument("--rebalance-threshold", type=float, default=1.0,
                   help="scales the average-load factor above which a join "
                        "line splits (sharded runs; default 1.0)")
    p.add_argument("--hash-function", default="MD5", help=argparse.SUPPRESS)
    p.add_argument("--encoding", default="utf-8",
                   help="input charset; 'auto' sniffs a BOM per file "
                        "(default utf-8)")
    p.add_argument("--file-filter", default=None,
                   help="regex on input-file basenames (the reference's "
                        "file-filtered directory scan)")
    p.add_argument("--sharded-ingest", action="store_true",
                   help="each host parses only its file subset and donates "
                        "rows to its own devices (multi-host; no host holds "
                        "the full triple table; all four traversal "
                        "strategies run on the presharded arrays)")
    p.add_argument("--interning", choices=("auto", "partitioned",
                                           "replicated"), default="auto",
                   help="sharded-ingest dictionary mode: partitioned = each "
                        "host stores only its value-hash range (multi-host "
                        "default; decode is a collective), replicated = "
                        "every host holds the union; auto picks partitioned "
                        "when multi-host")
    p.add_argument("--no-native-ingest", action="store_true",
                   help="force the pure-Python ingest path")
    p.add_argument("--checkpoint-dir", default=None,
                   help="directory for stage-boundary AND mid-discover "
                        "per-pass checkpoints; re-runs with unchanged "
                        "inputs/flags resume from them (a preempted sharded "
                        "discover replays only its unfinished passes).  "
                        "Fault-tolerance knobs ride the environment: "
                        "RDFIND_STRICT=1 fail-fast, RDFIND_FAULTS=... "
                        "deterministic fault injection (see README, 'Fault "
                        "tolerance & resume')")
    p.add_argument("--retry-on-preempt", type=int, default=0, metavar="N",
                   help="in-driver preemption supervisor: on Preempted (or "
                        "an escaped fallback request), back off and re-enter "
                        "the run up to N times, resuming from the "
                        "mesh-portable progress snapshots — even on a "
                        "shrunken device set.  0 (default) keeps the "
                        "historical exit-75 behavior for an external "
                        "orchestrator; RDFIND_RETRY_ON_PREEMPT is the env "
                        "form (the flag wins)")
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not args.projection or not set(args.projection) <= set("spo"):
        # Otherwise typo'd fields are silently dropped (zero or partial
        # output) — a long-standing footgun.
        parser.error(f"--projection {args.projection!r} must be a non-empty "
                     f"subset of 'spo'")
    if args.deletes and not args.delta_base:
        parser.error("--deletes only applies to incremental runs; pass "
                     "--delta BASE_DIR")
    if not args.inputs and not (args.delta_base and args.deletes):
        parser.error("no input files (positional inputs may only be empty "
                     "for a delete-only --delta run with --deletes)")
    if args.delta_base:
        for flag, bad in (("--sharded-ingest", args.sharded_ingest),
                          ("--only-read", args.only_read),
                          ("--do-only-join", args.only_join),
                          ("--find-only-fcs", args.find_only_fcs),
                          ("--checkpoint-dir", args.checkpoint_dir)):
            if bad:
                parser.error(f"{flag} is not supported with --delta "
                             f"(the delta engine replays host-side against "
                             f"the base bundle)")
        if args.delta_state:
            print("note: --delta-state is ignored with --delta (the run "
                  "advances the base bundle in place)", file=sys.stderr)
            args.delta_state = None
    if args.dop > 1 and args.coordinator is None and \
            "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # Allow --dop on CPU-only hosts (the minicluster analog): request fake
        # host devices before the JAX backend initializes.  No effect if a real
        # multi-chip platform provides enough devices.
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count={args.dop}"
                                   ).strip()
    if args.coordinator is None and (args.num_hosts != 1
                                     or args.host_index != 0):
        parser.error("--num-hosts/--host-index require --coordinator "
                     "(without it this would run a full independent "
                     "single-host job)")
    if os.environ.get("JAX_PLATFORMS"):
        # Make the env request effective: this image's sitecustomize force-
        # sets jax_platforms at interpreter start, so an explicit env pin
        # (e.g. JAX_PLATFORMS=cpu for minicluster runs while the TPU tunnel
        # is held elsewhere) must be re-applied via the config.
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if args.coordinator:
        # Join the multi-host runtime before anything touches the backend;
        # the mesh then spans every host's devices and --dop defaults to all
        # of them.
        # ensure_distributed = initialize_multihost + bounded retry with
        # jittered backoff around the rendezvous (gloo wedges on loaded
        # boxes) + the collective watchdog's deadman.
        from ..parallel.mesh import ensure_distributed
        ensure_distributed(args.coordinator, args.num_hosts, args.host_index)
        import jax
        if args.dop == 1:
            args.dop = jax.device_count()
        elif args.dop != jax.device_count():
            # A mesh over a device subset would exclude whole processes and
            # deadlock the collectives.
            parser.error(
                f"--dop {args.dop} does not span the multi-host runtime "
                f"({jax.device_count()} devices across {args.num_hosts} "
                f"hosts); omit --dop or pass the global device count")
    from ..runtime import driver  # deferred: must follow XLA_FLAGS setup

    cfg = driver.Config(
        input_paths=args.inputs,
        prefix_paths=args.prefixes,
        min_support=args.support,
        traversal_strategy=args.traversal_strategy,
        projections=args.projection,
        use_frequent_item_set=args.use_fis,
        use_association_rules=args.use_ars,
        clean_implied=args.clean_implied,
        distinct_triples=args.distinct_triples,
        asciify_triples=args.asciify_triples,
        tabs=args.tabs,
        only_read=args.only_read,
        only_join=args.only_join,
        output_file=args.output,
        ar_output_file=args.ar_output,
        collect_result=args.collect_result,
        debug_level=args.debug_level,
        counter_level=args.counter_level,
        n_devices=args.dop,
        retry_on_preempt=args.retry_on_preempt,
        native_ingest=not args.no_native_ingest,
        checkpoint_dir=args.checkpoint_dir,
        explicit_threshold=args.explicit_threshold,
        sbf_bits=args.sbf_bits,
        balanced_11=args.balanced_11,
        print_plan=args.print_plan,
        profile_dir=args.profile_dir,
        encoding=args.encoding,
        file_filter=args.file_filter,
        rebalance_strategy=args.rebalance_strategy,
        rebalance_threshold=args.rebalance_threshold,
        rebalance_max_load=args.rebalance_max_load,
        merge_window_size=args.merge_window_size,
        combinable_join=not args.no_combinable_join,
        collector=args.collector,
        find_only_fcs=args.find_only_fcs,
        create_join_histogram=args.create_join_histogram,
        sharded_ingest=args.sharded_ingest,
        interning=args.interning,
        trace_dir=args.trace_dir,
        metrics_file=args.metrics_file,
        console_port=args.console_port,
        delta_base=args.delta_base,
        delta_state=args.delta_state,
        delete_paths=args.deletes,
    )
    # Un-silence the remaining compatibility no-ops (the reference's
    # JVM-dataflow levers that the TPU design subsumes).
    for name, why in (
            ("no_bulk_merge", "merging is always windowed segment-sum here"),
            ("frequent_condition_strategy",
             "frequency uses exact segment counts; both reference strategies "
             "produce identical verdicts"),
            ("rebalance_split",
             "split lines always fan out to every device in the mesh"),
            ("hash_bytes", "hash dictionary subsumed by exact interning"),
            ("apply_hash", "hash dictionary subsumed by exact interning"),
            ("hash_dictionary", "hash dictionary subsumed by exact interning"),
            ("hash_function", "hash dictionary subsumed by exact interning"),
            ("find_frequent_captures",
             "exact capture-support pruning is always on"),
            ("rebalance_join",
             "the skew engine is always on for sharded runs; tune it with "
             "--rebalance-threshold/--rebalance-max-load"),
            ("only_read_compat", "use --only-read"),
            ("any_binary_captures",
             "binary condition frequencies are computed exactly in the same "
             "pass as unary ones; there is no pre-pass to skip")):
        v = getattr(args, name, None)
        default = {"rebalance_split": 1, "frequent_condition_strategy": 0,
                   "hash_bytes": -1, "hash_function": "MD5"}.get(name, False)
        if v not in (default, None):
            print(f"note: --{name.replace('_', '-')} has no effect ({why})",
                  file=sys.stderr)
    from ..runtime import faults

    from ..runtime import delta as delta_rt

    try:
        result = driver.run(cfg)
    except delta_rt.DeltaBaseError as e:
        # Clean miss, never a wrong incremental answer: name the failure
        # and tell the caller how to rebuild.
        print(f"rdfind: delta base unusable ({e}); re-run a full build "
              f"with --delta-state to rebuild the bundle", file=sys.stderr)
        return 66  # EX_NOINPUT: the base bundle cannot serve this run
    except faults.Preempted as e:
        # Injected (or test-driven) preemption: in-flight progress was
        # flushed before the raise; the same command resumes the run.
        print(f"rdfind: preempted ({e}); re-run with the same "
              f"--checkpoint-dir to resume from the last committed pass "
              f"(or pass --retry-on-preempt N to let the driver retry "
              f"in-process)", file=sys.stderr)
        return 75  # EX_TEMPFAIL: transient, retry the same invocation
    if not (cfg.output_file or cfg.collect_result):
        print(f"Detected {len(result.table)} CINDs.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
