"""CLI driver programs — the L6 layer (rdfind-algorithm/.../programs/): RDFind plus
the statistics oracles CountTriples, CountConditions, CountDistinctValues,
CheckHashCollisions."""
