"""CountConditions: histogram of capture sizes per condition type.

The reference (programs/CountConditions.scala:192-214) counts, for each unary and
binary condition type, how many conditions reach each size (distinct projected
values).  Used as a ground-truth oracle for pruning thresholds.
"""

from __future__ import annotations

import argparse
import sys
from collections import Counter, defaultdict

from .. import conditions as cc
from ..io import ntriples, reader

_FIELD_BITS = (cc.SUBJECT, cc.PREDICATE, cc.OBJECT)


def condition_size_histograms(triples, projections="spo"):
    """capture code -> {size -> count of conditions with that many distinct values}."""
    ext = defaultdict(set)
    proj_bits = [b for chx, b in zip("spo", _FIELD_BITS) if chx in projections]
    for t in triples:
        for proj_bit in proj_bits:
            pi = cc.FIELD_INDEX[proj_bit]
            a, b = [i for i in range(3) if i != pi]
            bit_a, bit_b = _FIELD_BITS[a], _FIELD_BITS[b]
            ext[(cc.create(bit_a, secondary_condition=proj_bit), t[a], None)].add(t[pi])
            ext[(cc.create(bit_b, secondary_condition=proj_bit), t[b], None)].add(t[pi])
            ext[(cc.create(bit_a, bit_b, proj_bit), t[a], t[b])].add(t[pi])
    hists: dict[int, Counter] = defaultdict(Counter)
    for (code, _, _), values in ext.items():
        hists[code][len(values)] += 1
    return hists


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="count-conditions")
    p.add_argument("inputs", nargs="+")
    p.add_argument("--projection", default="spo")
    args = p.parse_args(argv)
    paths = reader.resolve_path_patterns(args.inputs)
    is_nq = paths[0].endswith((".nq", ".nq.gz"))
    triples = [t for _, line in reader.iter_lines(paths)
               if (t := ntriples.parse_line(line, expect_quad=is_nq)) is not None]
    hists = condition_size_histograms(triples, args.projection)
    for code in sorted(hists):
        total = sum(hists[code].values())
        kind = "unary" if cc.is_unary(code) else "binary"
        print(f"capture code {code} ({kind}): {total} conditions")
        for size in sorted(hists[code]):
            print(f"  size {size}: {hists[code][size]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
