"""CountTriples: count non-comment input lines (programs/CountTriples.scala:46-66)."""

from __future__ import annotations

import argparse
import sys

from ..io import reader


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="count-triples")
    p.add_argument("inputs", nargs="+")
    args = p.parse_args(argv)
    paths = reader.resolve_path_patterns(args.inputs)
    n = sum(1 for _ in reader.iter_lines(paths, skip_comments=True))
    print(f"Counted {n} triples.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
