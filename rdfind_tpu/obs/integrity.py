"""The integrity plane: order/mesh-invariant content digests + verification.

The whole arc of this reproduction rests on a bit-identical-outputs
discipline, but until now it was enforced only inside pytest.  In production
there is no observer: a bit flipped by a flaky host pull, a torn snapshot
that loads as plausible rows, or a divergent replica after an elastic
re-shard would silently corrupt the CIND output.  This module is the
correctness counterpart of the PR-9 timing plane and the PR-11 data plane
(the paper's own CheckHashCollisions driver acknowledges the same risk
class).

Digest construction.  A stage's content digest is two 32-bit lanes, each a
wraparound (mod 2^32) sum over the per-row splitmix32 mixes of the row's
column tuple (ops/hashing.hash_cols semantics) under two independent seeds
(~64-bit collision resistance; a plain sum under ONE seed is forgeable by
swapping two rows' contributions, two independently-mixed lanes are not).
Because the fold is a commutative sum it is

  * order-invariant  — collect_blocks concatenation order, the elastic
    _reshard_pass_rows permutation, and the pass partition all wash out;
  * mesh-invariant   — per-device partial sums psum to the identical global
    value at mesh 8 and mesh 2 (int32 two's-complement psum wraparound IS
    uint32 wraparound, bit for bit), exactly the property PR-14 elastic
    resume needs to verify snapshots across mesh sizes.

On device the lanes ride the existing packed telemetry (models/sharded.py
appends them to the pass lane array) so they cost no extra host syncs; this
module holds the numpy host replica that re-verifies pulled blocks and
loaded snapshots against those lanes.

Gating clones the datastats policy: ``RDFIND_INTEGRITY=0`` forces off,
``=1`` forces on, default follows the live obs consumers (tracer, metrics
exposition, console).  ``RDFIND_INTEGRITY_STRICT=1`` turns a verification
mismatch into a failed run (IntegrityError); the default records a named
``integrity`` degradation and continues flagged.

Stdlib-only at import time (the obs contract); numpy is imported lazily
inside the digest helpers.
"""

from __future__ import annotations

import json
import os

from . import metrics, tracer

# The two lane seeds (ops/hashing.hash_cols seed space; keep clear of the
# exchange/planner seeds in models/sharded.py — same mixer, and a digest
# colliding with a routing hash would correlate failure modes).
SEED_A = 29
SEED_B = 43

MASK32 = 0xFFFFFFFF


class IntegrityError(RuntimeError):
    """A digest verification failed under RDFIND_INTEGRITY_STRICT=1 (or a
    replica divergence that no retry can repair)."""


def enabled() -> bool:
    """Whether integrity verification should run.

    ``RDFIND_INTEGRITY=0`` forces it off, ``=1`` forces it on; by default it
    follows the consumers — live exactly when the tracer, the Prometheus
    exposition, or the run console could show the result (the PR-5 rule: no
    verification work without a consumer).  The device digest lanes are
    computed unconditionally (one compiled program either way — knob-off
    bit-identity); only the host-side recompute/verify/publish is gated.
    """
    v = os.environ.get("RDFIND_INTEGRITY", "").strip()
    if v == "0":
        return False
    if v == "1":
        return True
    if tracer.enabled() or metrics.export_requested():
        return True
    from . import console
    return console.serving()


def strict() -> bool:
    """RDFIND_INTEGRITY_STRICT=1: a verification mismatch fails the run
    instead of degrading it."""
    return os.environ.get("RDFIND_INTEGRITY_STRICT", "").strip() == "1"


# ---------------------------------------------------------------------------
# Host digest replicas (numpy, uint32 wraparound — must match the device
# lanes from ops/hashing.digest_fold bit for bit).
# ---------------------------------------------------------------------------


def _mix32(x):
    import numpy as np
    x = np.asarray(x).astype(np.uint32)
    x = (x ^ (x >> np.uint32(16))) * np.uint32(0x85EBCA6B)
    x = (x ^ (x >> np.uint32(13))) * np.uint32(0xC2B2AE35)
    return x ^ (x >> np.uint32(16))


def row_mixes(cols, seed: int):
    """Per-row hash_cols mixes (uint32 array) — the summands of _fold.

    Exposed so incremental consumers (runtime/delta.py's per-pass digest
    maintenance) can subtract removed rows and add inserted rows from a
    stored lane sum in O(change): the lanes are plain mod-2^32 sums of
    these mixes, so a digest update never needs the unchanged rows.
    """
    import numpy as np
    with np.errstate(over="ignore"):
        h = np.uint32(0x9E3779B9 * (seed + 1) & MASK32)
        for c in cols:
            h = _mix32(np.asarray(c).astype(np.uint32)
                       ^ (h + np.uint32(0x9E3779B9)))
        return np.asarray(h, np.uint32)


def _fold(cols, seed: int) -> int:
    """Wraparound-uint32 sum of the per-row hash_cols mixes of `cols`."""
    import numpy as np
    h = row_mixes(cols, seed)
    if h.ndim == 0:
        return int(h)
    return int(np.sum(h.reshape(-1), dtype=np.uint32))


def digest_rows(cols) -> tuple[int, int]:
    """Order-invariant (lane_a, lane_b) digest of a row set given as aligned
    columns (every row assumed valid — host blocks are already compacted)."""
    return _fold(cols, SEED_A), _fold(cols, SEED_B)


def digest_sketch_rows(table_rows, bits: int) -> tuple[int, int]:
    """Digest of concatenated per-device (bits,) count-min partials: each row
    hashes as its (local position, value) pair — position-dependence matters
    for a dense table, and local positions repeat every `bits` rows however
    many partials are stacked, so the fold matches the device lanes at any
    mesh size with the same `bits`."""
    import numpy as np
    t = np.asarray(table_rows).reshape(-1)
    pos = np.arange(t.shape[0], dtype=np.int64) % max(int(bits), 1)
    return digest_rows([pos, t])


def digest_table(table) -> tuple[int, int]:
    """Order-invariant digest of a CindTable (the run's output digest —
    identical across strategies, mesh sizes, and knob settings whenever the
    logical CIND set is)."""
    cols = [table.dep_code, table.dep_v1, table.dep_v2, table.ref_code,
            table.ref_v1, table.ref_v2, table.support]
    return digest_rows(cols)


def lanes_to_digest(lane_a, lane_b) -> tuple[int, int]:
    """Telemetry lanes ride as int32 (psum-friendly); read them back as the
    uint32 values the host replicas produce."""
    return int(lane_a) & MASK32, int(lane_b) & MASK32


def digest_hex(a: int, b: int) -> str:
    return f"{a & MASK32:08x}{b & MASK32:08x}"


# ---------------------------------------------------------------------------
# Publishing (through the metrics shims; all consumers — the legacy stats
# dict, Prometheus, the console /integrity endpoint — see one schema).
# ---------------------------------------------------------------------------


def publish_stage(stats: dict | None, stage: str, a: int, b: int,
                  **detail) -> None:
    """Record one verified stage digest: the integrity_stages mapping (the
    run certificate's body), a trace instant, and the verified counter."""
    metrics.mapping_set(stats, "integrity_stages", stage, digest_hex(a, b))
    metrics.counter_add(stats, "integrity_verified")
    tracer.instant(f"integrity:{stage}", cat=tracer.CAT_RUN,
                   digest=digest_hex(a, b), **detail)


def publish_output(stats: dict | None, table) -> None:
    """Stamp a strategy's final-table digest as the ``output`` stage (the
    single-device models' one-line hook; the sharded strategies publish the
    same digest, so twins agree by construction)."""
    if stats is None or not enabled():
        return
    publish_stage(stats, "output", *digest_table(table))


def note_mismatch(stats: dict | None, *, site: str, stage: str,
                  pass_idx=None, repaired: bool = False) -> None:
    """Record one detected digest mismatch (named: site + stage/pass) and
    push the verdict onto the heartbeat so tpu_watch can report CORRUPT."""
    metrics.counter_add(stats, "integrity_mismatches")
    if repaired:
        metrics.counter_add(stats, "integrity_repaired")
    detail = {"site": site, "stage": stage, "repaired": repaired}
    if pass_idx is not None:
        detail["pass"] = int(pass_idx)
    metrics.list_append(stats, "integrity_events", detail)
    tracer.instant("integrity_mismatch", cat=tracer.CAT_RUN, **detail)
    if not repaired:
        tracer.set_status(integrity={"corrupt": True, "site": site,
                                     "stage": stage})


def summarize(stats: dict | None) -> dict:
    """Fold the counters into the ``stats["integrity"]`` struct (numeric
    leaves land in Prometheus automatically via _prom_emit)."""
    src = stats if stats is not None else {}
    summary = {
        "enabled": enabled(),
        "strict": strict(),
        "verified": int(src.get("integrity_verified", 0)),
        "mismatches": int(src.get("integrity_mismatches", 0)),
        "repaired": int(src.get("integrity_repaired", 0)),
    }
    metrics.struct_set(stats, "integrity", summary)
    return summary


# ---------------------------------------------------------------------------
# The run certificate: input signature -> per-stage digests -> output digest,
# provenance-keyed like BENCH_HISTORY rows — the artifact a serving layer (or
# a re-run) can check a result set against.
# ---------------------------------------------------------------------------


def run_certificate(*, input_signature, stages: dict, output_digest: str,
                    provenance: dict, extra: dict | None = None) -> dict:
    cert = {
        "format": 1,
        "input_signature": input_signature,
        "stages": dict(stages or {}),
        "output_digest": output_digest,
        "provenance": provenance,
    }
    if extra:
        cert.update(extra)
    return cert


def write_certificate(path: str, cert: dict) -> None:
    """Atomic certificate write (tmp + rename; a reader never sees a torn
    JSON)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(cert, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def certificate_path() -> str | None:
    """Where to write the run certificate: RDFIND_CERT names a path
    explicitly; otherwise it lands next to the heartbeat in the live trace
    directory when tracing is armed; otherwise nowhere (the stats struct
    still carries the digests)."""
    p = os.environ.get("RDFIND_CERT", "").strip()
    if p:
        return p
    d = tracer.trace_dir()
    if d:
        return os.path.join(d, "run_certificate.json")
    return None
