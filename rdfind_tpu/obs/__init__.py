"""Unified observability: span tracing, metrics registry, HBM watermarks.

The reference publishes accumulators on every run (AbstractFlinkProgram.java:
65-77,175-182 — "always report"); this reproduction outgrew that discipline
piecemeal (DispatchStats, the exchange ledger, ingest lanes, the fault
ladder each with its own dict keys and print format).  ``obs`` is the one
surface they all publish through:

  tracer    hierarchical host spans (run -> stage -> pass -> dispatch/pull/
            exchange/checkpoint) recorded as JSONL events per host and
            exportable as Chrome-trace JSON (Perfetto); host spans emit
            matching jax.profiler.TraceAnnotations so an XLA --profile-dir
            trace lines up with the host timeline.  Off by default and
            near-free when disabled.
  metrics   typed counters/gauges/histograms mirroring every legacy
            ``stats`` key bit-for-bit (the publish shims update both), with
            optional Prometheus text exposition to a file.
  memory    per-pass HBM high-water marks + allocation deltas from jax
            memory stats, with a near-cap warning that fires BEFORE the
            degradation ladder does.
  report    the per-host trace merge tool, Chrome-trace export, and the ONE
            formatter behind --debug / -c counter rendering.
  heartbeat the run's liveness/status file (current stage, pass, last-event
            timestamp) so a wedged run is distinguishable from a slow one.
  flightrec crash-surviving bounded ring of the last N events, dumped
            atomically on signals, fault-ladder rungs and preemptions — the
            post-mortem when the jsonl tracer was off.
  sentinel  the BENCH_HISTORY.jsonl perf series + the noise-aware
            regression gate (``python -m rdfind_tpu.obs.sentinel --check``).

Import-light by design: every submodule is stdlib-only at import time (jax
is imported lazily at call sites), so runtime/dispatch.py and
runtime/faults.py can depend on obs without widening their import footprint.
"""

from __future__ import annotations

from . import (console, datastats, flightrec, forecast,  # noqa: F401
               heartbeat, memory, metrics, report, sentinel, tracer)


def active() -> bool:
    """Whether any obs output is live (tracing, metrics exposition, or the
    run console) — the gate for sampling work that is pure overhead without
    a consumer (e.g. per-pass HBM watermark reads)."""
    return (tracer.enabled() or metrics.export_requested()
            or console.serving())


def snapshot() -> dict:
    """One JSON-able snapshot of everything obs knows right now: the
    metrics registry (dispatch + exchange + ingest + fault telemetry) and
    the current device-memory watermarks.  Embedded by bench.py in its
    detail rows so every BENCH_* artifact carries one schema."""
    return {
        "metrics": metrics.registry().snapshot(jsonable=True),
        "memory": memory.sample(None, publish=False),
    }
