"""Cap-exhaustion forecasting: name the cap BEFORE the ladder fires.

The PR-3 degradation ladder (grow -> split -> skip -> fallback) reacts to an
overflow that already happened; the HBM watermark warning (obs/memory.py)
predicts memory pressure but says nothing about the pair/giant/DCN caps.
This module closes that gap: fed the per-pass cap-utilization fractions
(obs/datastats.py's trajectory points), it fits each cap's trajectory with
a least-squares line and emits an advisory — registry entry, trace instant,
heartbeat extra, ``--debug`` line — naming the cap and the predicted
exhaustion pass while there is still time to restart with a bigger
``RDFIND_PAIR_ROW_BUDGET`` or smaller shard.

Two triggers, first one wins per cap:

* **trend**: the fitted line crosses frac >= 1.0 at a pass the run still
  has ahead of it;
* **warn**: the current fraction already exceeds ``RDFIND_FORECAST_WARN``
  (default 0.85 — above the ~0.8 steady state the 1.25x headroom convention
  yields, i.e. demand ate the headroom).

``RDFIND_FORECAST=0`` disables, ``=1`` forces on; by default forecasting
follows :func:`datastats.enabled` (no consumer, no work).  Differentially
tested against ``runtime/faults.py`` injected overflow: the advisory must
land at least one pass before the grow rung.

Stdlib-only (the obs contract).
"""

from __future__ import annotations

import math
import os

from . import metrics, tracer

DEFAULT_WARN_FRAC = 0.85
# A cap needs this many trajectory points before the trend trigger can fire
# (a one-point "trend" is noise).
MIN_TREND_POINTS = 2


def enabled() -> bool:
    """``RDFIND_FORECAST``: "0" off, "1" force on; default follows the
    datastats consumers gate."""
    v = os.environ.get("RDFIND_FORECAST", "").strip()
    if v == "0":
        return False
    if v == "1":
        return True
    from . import datastats
    return datastats.enabled()


def warn_frac() -> float:
    try:
        return float(os.environ.get("RDFIND_FORECAST_WARN",
                                    str(DEFAULT_WARN_FRAC)))
    except ValueError:
        return DEFAULT_WARN_FRAC


def predict_exhaustion(trajectory: list[tuple[int, float]]) -> int | None:
    """First pass index where the least-squares fit of (pass, frac) crosses
    1.0, or None when the trajectory is too short, flat, or falling."""
    if len(trajectory) < MIN_TREND_POINTS:
        return None
    n = len(trajectory)
    mx = sum(p for p, _ in trajectory) / n
    my = sum(f for _, f in trajectory) / n
    denom = sum((p - mx) ** 2 for p, _ in trajectory)
    if denom <= 0:
        return None
    slope = sum((p - mx) * (f - my) for p, f in trajectory) / denom
    if slope <= 0:
        return None
    intercept = my - slope * mx
    return max(trajectory[-1][0] + 1,
               math.ceil((1.0 - intercept) / slope))


class Forecaster:
    """Per-executor advisory engine: feed it each pass's utilization
    fractions; it publishes at most one advisory per cap."""

    def __init__(self, stats: dict | None, n_pass: int, phase: str = "",
                 warn: float | None = None):
        self.stats = stats
        self.n_pass = int(n_pass)
        self.phase = phase
        self.warn = warn_frac() if warn is None else float(warn)
        self._trajectories: dict[str, list[tuple[int, float]]] = {}
        self._advised: set[str] = set()

    def step(self, pass_idx: int, fracs: dict[str, float]) -> list[dict]:
        """Record one trajectory point per cap; returns the advisories
        newly raised this pass (usually empty)."""
        raised = []
        for cap in sorted(fracs):
            frac = float(fracs[cap])
            traj = self._trajectories.setdefault(cap, [])
            traj.append((int(pass_idx), frac))
            if cap in self._advised:
                continue
            predicted = predict_exhaustion(traj)
            if frac >= self.warn:
                reason = "warn"
                predicted = (int(pass_idx) + 1 if predicted is None
                             else predicted)
            elif predicted is not None and predicted < self.n_pass:
                reason = "trend"
            else:
                continue
            self._advised.add(cap)
            adv = {"cap": cap, "phase": self.phase, "pass": int(pass_idx),
                   "predicted_pass": int(predicted),
                   "frac": round(frac, 6), "n_pass": self.n_pass,
                   "reason": reason}
            publish_advisory(self.stats, adv)
            raised.append(adv)
        return raised


def publish_advisory(stats: dict | None, adv: dict) -> None:
    """One advisory's full fan-out: registry mapping + active gauge, trace
    instant, heartbeat extra (what tpu_watch --status reads as
    "degrading"), and a stderr line under --debug via format_lines."""
    metrics.mapping_set(stats, "cap_forecast", adv["cap"], adv)
    metrics.gauge_set(stats, "cap_forecast_active", 1)
    tracer.instant("cap_forecast", cat=tracer.CAT_PASS, **adv)
    tracer.set_status(forecast={
        "cap": adv["cap"], "predicted_pass": adv["predicted_pass"],
        "frac": adv["frac"], "reason": adv["reason"]})


def advisory_line(adv: dict) -> str:
    """The one shared rendering of an advisory (report --summary and the
    --debug formatter both call this, so they can't fork)."""
    phase = f" [{adv['phase']}]" if adv.get("phase") else ""
    return (f"forecast{phase}: cap {adv['cap']} predicted exhausted at pass "
            f"{adv['predicted_pass']}/{adv.get('n_pass', '?')} "
            f"({adv['reason']}: frac {adv['frac']:.3f} at pass "
            f"{adv['pass']})")


def format_lines(stats: dict) -> list[str]:
    """Advisory lines from a published stats dict (empty when none fired)."""
    forecast = stats.get("cap_forecast") or {}
    return [advisory_line(forecast[cap]) for cap in sorted(forecast)]
