"""Per-pass data-distribution telemetry: what the data looked like.

PRs 5 and 9 instrumented the *mechanisms* (spans, registry, HBM watermarks,
collective timing, host skew) but nothing observed the *data*: a run that
degrades today says the ladder fired, never which join-line distribution
blew a cap or how much headroom remained.  This module is the data plane:

* log2-bucketed join-line size histograms (sharded: computed on-device and
  pulled as 32 ints; single-device: from the host-resident length arrays),
* capture support spectra (same log2 buckets over capture cardinalities),
* per-cap utilization fractions — used/planned for lines, captures, pairs
  and the PR-8 ``*_dcn`` caps, measured at plan time from the exact
  pre-headroom gathers and per-pass from the telemetry tail lanes,
* block-skip effectiveness (``n_blocks_skipped``/total from the PR-6
  dense plan) and giant-line share.

Everything publishes through the sanctioned metrics shims so the legacy
``stats`` dicts, the registry mirror, Prometheus exposition and the console
``/datastats`` endpoint all see one schema.  Sampling follows the PR-5
disabled-path discipline: :func:`enabled` is False unless a consumer is
live (tracer, metrics exposition, or the run console) or the
``RDFIND_DATASTATS`` knob forces it, and the disabled path is one env read
plus three flag checks (bounded by the same <2% overhead test shape as the
tracer).

Stdlib-only at import time (the obs contract); numpy is imported lazily
inside the helpers that bucket host arrays.
"""

from __future__ import annotations

import os

from . import metrics, tracer

# Join lines and capture supports are int32-bounded, so 32 log2 buckets
# cover every representable size; bucket e counts values in [2^e, 2^(e+1)).
N_BUCKETS = 32


def enabled() -> bool:
    """Whether data-distribution sampling should run.

    ``RDFIND_DATASTATS=0`` forces it off, ``=1`` forces it on; by default it
    follows the consumers — live exactly when the tracer, the Prometheus
    exposition, or the run console could show the result (the PR-5 rule: no
    sampling work without a consumer).
    """
    v = os.environ.get("RDFIND_DATASTATS", "").strip()
    if v == "0":
        return False
    if v == "1":
        return True
    if tracer.enabled() or metrics.export_requested():
        return True
    from . import console
    return console.serving()


# ---------------------------------------------------------------------------
# Bucketing helpers (host side; the sharded path buckets on-device and only
# pulls the 32-int bin vector through hist_from_bins).
# ---------------------------------------------------------------------------


def log2_bucket_counts(sizes) -> dict[int, int]:
    """Sparse {exponent: count} histogram of positive sizes; bucket ``e``
    holds values in [2^e, 2^(e+1)).  Zero/negative entries are dropped
    (padding rows, masked lines)."""
    import numpy as np
    a = np.asarray(sizes).reshape(-1)
    if a.size == 0:
        return {}
    a = a[a > 0].astype(np.int64)
    if a.size == 0:
        return {}
    exp = np.minimum(np.int64(N_BUCKETS - 1),
                     np.floor(np.log2(a)).astype(np.int64))
    counts = np.bincount(exp, minlength=N_BUCKETS)
    return {int(e): int(c) for e, c in enumerate(counts) if c}


def hist_from_bins(bins) -> dict[int, int]:
    """Sparse dict from a dense 32-bin vector (the on-device histogram's
    pulled output)."""
    return {int(e): int(c) for e, c in enumerate(bins) if int(c)}


def _hist_struct(hist: dict[int, int]) -> dict[str, int]:
    """JSON/Prometheus-friendly key form: bucket 3 -> "b3"."""
    return {f"b{e}": int(c) for e, c in sorted(hist.items())}


# ---------------------------------------------------------------------------
# Publishers.  Each writes ONE struct key through the shims; call sites gate
# on enabled() so none of this work happens without a consumer.
# ---------------------------------------------------------------------------


def publish_line_stats(stats: dict | None, *, hist: dict[int, int],
                       n_lines: int, max_line: int, giant_lines: int = 0,
                       source: str = "host") -> None:
    """The join-line size distribution: how many lines at each log2 size,
    the largest line, and the giant-line share (the lines the sharded
    executor routes through the giant-pair path)."""
    n_lines = int(n_lines)
    metrics.struct_set(stats, "datastats_lines", {
        "n_lines": n_lines,
        "max_line": int(max_line),
        "giant_lines": int(giant_lines),
        "giant_share": round(int(giant_lines) / n_lines, 6) if n_lines else 0.0,
        "hist_log2": _hist_struct(hist),
        "source": source,
    })


def publish_capture_spectrum(stats: dict | None, *, hist: dict[int, int],
                             n_captures: int, max_support: int,
                             source: str = "host") -> None:
    """The capture support spectrum: how many captures at each log2
    support.  A spectrum dominated by the minimum support explains a
    pair-light run; a fat tail explains a cap-hungry one."""
    metrics.struct_set(stats, "datastats_captures", {
        "n_captures": int(n_captures),
        "max_support": int(max_support),
        "hist_log2": _hist_struct(hist),
        "source": source,
    })


def publish_block_skip(stats: dict | None, *, n_blocks: int,
                       n_blocks_skipped: int) -> None:
    """PR-6 block-skip effectiveness: the fraction of dense cooc tiles the
    skew-driven sub-tile skipping never dispatched."""
    n_blocks = int(n_blocks)
    skipped = int(n_blocks_skipped)
    metrics.struct_set(stats, "datastats_block_skip", {
        "n_blocks": n_blocks,
        "n_blocks_skipped": skipped,
        "skip_frac": round(skipped / n_blocks, 6) if n_blocks else 0.0,
    })


def publish_cap_utilization(stats: dict | None, planned: dict,
                            used: dict) -> None:
    """Plan-time cap utilization: for every cap with a measured demand
    (the exact pre-headroom gathers), {planned, used, frac}.  frac ~0.8 is
    the healthy steady state under the 1.25x headroom convention; frac near
    1.0 means the next skew spike rides the degradation ladder."""
    out = {}
    for cap, demand in used.items():
        cap_v = planned.get(cap)
        if not cap_v:
            continue
        out[cap] = {"planned": int(cap_v), "used": int(demand),
                    "frac": round(int(demand) / int(cap_v), 6)}
    if out:
        metrics.struct_set(stats, "cap_utilization", out)


def publish_pass_utilization(stats: dict | None, pass_idx: int,
                             fracs: dict[str, float]) -> dict:
    """Per-pass cap-utilization trajectory point (the forecaster's input):
    appended to ``cap_utilization_passes`` and emitted as a Chrome-trace
    counter so Perfetto plots the climb toward 1.0."""
    entry = {"pass": int(pass_idx)}
    entry.update({k: round(float(v), 6) for k, v in sorted(fracs.items())})
    metrics.list_append(stats, "cap_utilization_passes", entry)
    tracer.counter("cap_utilization", **entry)
    tracer.set_status(cap_util=dict(entry))
    return entry
