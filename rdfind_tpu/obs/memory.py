"""Device-memory (HBM) watermarks from jax memory stats.

The degradation ladder (runtime/faults.py) reacts to capacity exhaustion
AFTER a buffer overflows; the watermark samples here surface pressure
BEFORE that: per-pass high-water marks and allocation deltas, published
into the trace (as a Chrome-trace counter lane) and the metrics registry,
with a near-cap warning once in-use bytes cross RDFIND_HBM_WARN_FRAC
(default 0.9) of the device limit.

``jax.Device.memory_stats()`` is populated on TPU/GPU backends and returns
None (or raises) on CPU — sampling degrades to a no-op there, so the
8-device CPU proxy tests drive this module through the ``_stats_fn`` seam.

Stdlib-only at import time; jax is imported lazily per sample.
"""

from __future__ import annotations

import os
import sys

from . import metrics, tracer

DEFAULT_WARN_FRAC = 0.9

# Test seam: replace with a callable returning [(device_label, stats_dict)]
# to drive watermark logic without a real TPU.
_stats_fn = None

# Last-sample in-use bytes per device label (allocation deltas).
_last_in_use: dict[str, int] = {}
_warned_labels: set[str] = set()


def warn_frac() -> float:
    try:
        return float(os.environ.get("RDFIND_HBM_WARN_FRAC", DEFAULT_WARN_FRAC))
    except ValueError:
        return DEFAULT_WARN_FRAC


def _device_memory_stats() -> list[tuple[str, dict]]:
    if _stats_fn is not None:
        return list(_stats_fn())
    try:
        import jax
        out = []
        for d in jax.local_devices():
            try:
                st = d.memory_stats()
            except Exception:
                st = None
            if st:
                out.append((str(d), st))
        return out
    except Exception:
        return []


def reset() -> None:
    """Forget delta baselines and warning latches (run boundaries, tests)."""
    _last_in_use.clear()
    _warned_labels.clear()


def sample(stats: dict | None, label: str = "", publish: bool = True):
    """One watermark sample across the local devices.

    Returns the aggregate record (or None when no backend reports memory):
    {"in_use_bytes", "peak_bytes", "limit_bytes", "frac", "delta_bytes"} —
    maxima across devices (min for the limit), `frac` the worst device's
    in-use fraction of its limit, `delta_bytes` the largest in-use change
    since the previous sample (the allocation delta of whatever ran between
    the two, e.g. one dep-slice pass).

    With `publish`, the record lands in stats["hbm"] / the registry (via the
    struct shim), the per-device in-use bytes ride the trace as a counter
    lane, and crossing the warn fraction emits a once-per-device stderr
    warning + trace instant + `hbm_near_cap_warnings` counter.
    """
    per_dev = _device_memory_stats()
    if not per_dev:
        return None
    in_use = peak = delta = 0
    limit = None
    frac = 0.0
    counters = {}
    warn_at = warn_frac()
    for dev, st in per_dev:
        u = int(st.get("bytes_in_use", 0))
        p = int(st.get("peak_bytes_in_use", u))
        lim = int(st.get("bytes_limit", 0))
        in_use = max(in_use, u)
        peak = max(peak, p)
        if lim > 0:
            limit = lim if limit is None else min(limit, lim)
            frac = max(frac, u / lim)
        delta = max(delta, u - _last_in_use.get(dev, u))
        _last_in_use[dev] = u
        counters[dev] = u
        if publish and lim > 0 and u / lim >= warn_at \
                and dev not in _warned_labels:
            _warned_labels.add(dev)
            print(f"warning: HBM near cap on {dev}: {u}/{lim} bytes "
                  f"({u / lim:.0%} >= {warn_at:.0%})"
                  + (f" at {label}" if label else "")
                  + "; the degradation ladder may fire next",
                  file=sys.stderr)
            tracer.instant("hbm_near_cap", cat="memory", device=dev,
                           bytes_in_use=u, bytes_limit=lim, label=label)
            metrics.counter_add(stats, "hbm_near_cap_warnings")
    record = {"in_use_bytes": in_use, "peak_bytes": peak,
              "limit_bytes": limit if limit is not None else 0,
              "frac": round(frac, 4), "delta_bytes": delta}
    if publish:
        metrics.struct_set(stats, "hbm", record)
        metrics.observe("hbm_in_use_bytes", in_use)
        tracer.counter("hbm_bytes_in_use", **counters)
        if label:
            tracer.instant("hbm_watermark", cat="memory", label=label,
                           **record)
    return record
