"""Hierarchical host-span tracing, recorded as per-host JSONL event files.

Span model: run -> stage -> pass -> dispatch / pull / exchange / checkpoint.
Every span is written as a Chrome-trace B/E event pair the moment it opens
and closes (never buffered until run end), so a wedged run's trace still
shows exactly which span it died inside.  ``report.export_chrome_trace``
turns the event files into one Chrome-trace JSON with per-host lanes,
viewable in Perfetto (ui.perfetto.dev) or chrome://tracing.

Timestamps are epoch microseconds (time.time_ns) — the only clock multiple
hosts share — and the merge tool rebases them to the earliest event.

Host/device alignment: when tracing is enabled each span also enters a
``jax.profiler.TraceAnnotation`` of the same name, so an XLA profiler trace
(--profile-dir) carries the host span names on its TraceMe timeline and the
two traces line up.  jax is imported lazily and only when tracing is ON.

Disabled cost: ``span()`` returns a shared no-op context manager after one
module-global check — the hot path pays a function call and a branch, which
the disabled-overhead smoke (tests/test_obs.py) bounds.

Stdlib-only at import time (the obs contract; runtime/dispatch.py imports
this module).
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import flightrec, heartbeat

# Span categories (the `cat` field of every event) — the fixed vocabulary the
# report tool and the tests nest-check against.
CAT_RUN = "run"
CAT_STAGE = "stage"
CAT_PASS = "pass"
CAT_DISPATCH = "dispatch"
CAT_PULL = "pull"
CAT_EXCHANGE = "exchange"
CAT_CHECKPOINT = "checkpoint"

EVENTS_PREFIX = "events-host"


class _NullSpan:
    """The shared disabled-path context manager (one instance, no state)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One open span: writes its E event (and pops the thread stack) on exit."""

    __slots__ = ("_tracer", "name", "_annotation")

    def __init__(self, tracer: "Tracer", name: str, annotation):
        self._tracer = tracer
        self.name = name
        self._annotation = annotation

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._annotation is not None:
            try:
                self._annotation.__exit__(*exc)
            except Exception:
                pass
        self._tracer._close_span(self.name)
        return False


class Tracer:
    """One per-process tracer bound to a trace directory.

    Not instantiated directly in pipeline code — use the module functions
    (``start`` / ``span`` / ``instant`` / ``stop``), which also keep the
    disabled path free.
    """

    def __init__(self, trace_dir: str, host_index: int = 0,
                 annotate: bool = True):
        self.dir = trace_dir
        self.host_index = int(host_index)
        os.makedirs(trace_dir, exist_ok=True)
        self._path = os.path.join(trace_dir,
                                  f"{EVENTS_PREFIX}{self.host_index}.jsonl")
        self._f = open(self._path, "a", buffering=1)  # line-buffered
        self._lock = threading.Lock()
        self._local = threading.local()
        self.n_events = 0
        self.n_mismatched = 0  # __exit__ order violations (bugs, not faults)
        self._status = {"stage": None, "pass": None}
        self._beat = heartbeat.Heartbeat(trace_dir, host_index=self.host_index)
        # jax.profiler.TraceAnnotation, resolved once (None off-jax).
        self._annotation_cls = None
        if annotate:
            try:
                from jax.profiler import TraceAnnotation
                self._annotation_cls = TraceAnnotation
            except Exception:
                self._annotation_cls = None

    # -- internals ----------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _emit(self, ev: dict) -> None:
        if flightrec._ENABLED:
            flightrec.record(ev)
        line = json.dumps(ev, separators=(",", ":"), default=str)
        with self._lock:
            self._f.write(line + "\n")
            self.n_events += 1

    def _tid(self) -> int:
        return threading.get_ident() & 0xFFFF

    def open_span(self, name: str, cat: str, args: dict):
        stack = self._stack()
        stack.append(name)
        if cat == CAT_STAGE:
            self._status["stage"] = name
            self._status["pass"] = None
        elif cat == CAT_PASS:
            self._status["pass"] = args.get("pass")
        self._emit({"name": name, "cat": cat, "ph": "B",
                    "ts": time.time_ns() // 1000, "pid": self.host_index,
                    "tid": self._tid(), "args": args})
        self._beat.maybe_beat(self._status)
        annotation = None
        if self._annotation_cls is not None:
            try:
                annotation = self._annotation_cls(name)
                annotation.__enter__()
            except Exception:
                annotation = None
        return _Span(self, name, annotation)

    def _close_span(self, name: str) -> None:
        stack = self._stack()
        if stack and stack[-1] == name:
            stack.pop()
        else:  # exits out of order: record, never raise mid-pipeline
            self.n_mismatched += 1
            if name in stack:
                stack.remove(name)
        self._emit({"name": name, "ph": "E",
                    "ts": time.time_ns() // 1000, "pid": self.host_index,
                    "tid": self._tid()})
        self._beat.maybe_beat(self._status)

    def instant(self, name: str, cat: str, args: dict) -> None:
        self._emit({"name": name, "cat": cat, "ph": "i", "s": "t",
                    "ts": time.time_ns() // 1000, "pid": self.host_index,
                    "tid": self._tid(), "args": args})

    def counter(self, name: str, values: dict) -> None:
        """A Chrome-trace counter sample (e.g. HBM bytes over time)."""
        self._emit({"name": name, "ph": "C", "ts": time.time_ns() // 1000,
                    "pid": self.host_index, "tid": 0, "args": values})

    def open_spans(self) -> int:
        return len(self._stack())

    def close(self) -> None:
        self._beat.beat(self._status, final=True)
        with self._lock:
            self._f.flush()
            self._f.close()


_TRACER: Tracer | None = None
_ENABLED = False


def enabled() -> bool:
    return _ENABLED


def current() -> Tracer | None:
    return _TRACER


def trace_dir() -> str | None:
    return _TRACER.dir if _TRACER is not None else None


def start(directory: str, host_index: int | None = None) -> Tracer:
    """Enable tracing into `directory` (idempotent per directory).

    `host_index` defaults to jax.process_index() when jax is already up,
    else 0 — passed explicitly by callers that know better.
    """
    global _TRACER, _ENABLED
    if _TRACER is not None and _TRACER.dir == directory:
        _ENABLED = True
        return _TRACER
    if _TRACER is not None:
        _TRACER.close()
    if host_index is None:
        host_index = 0
        try:
            import jax
            host_index = jax.process_index()
        except Exception:
            pass
    _TRACER = Tracer(directory, host_index=host_index)
    flightrec.set_host(host_index)
    _ENABLED = True
    return _TRACER


def stop() -> None:
    global _TRACER, _ENABLED
    if _TRACER is not None:
        _TRACER.close()
    _TRACER = None
    _ENABLED = False


def span(name: str, cat: str = CAT_STAGE, **args):
    """Open a span (context manager).  The disabled path returns a shared
    no-op object after one global check (plus one flight-recorder flag
    check — the ring records span opens even when the jsonl tracer is off,
    so post-mortem dumps exist for untraced runs)."""
    if not _ENABLED:
        if flightrec._ENABLED:
            flightrec.record({"name": name, "cat": cat, "ph": "B",
                              "ts": time.time_ns() // 1000, "args": args})
        return _NULL_SPAN
    return _TRACER.open_span(name, cat, args)


def instant(name: str, cat: str = CAT_EXCHANGE, **args) -> None:
    """A zero-duration event (e.g. one exchange dispatch's ledger entry)."""
    if not _ENABLED:
        if flightrec._ENABLED:
            flightrec.record({"name": name, "cat": cat, "ph": "i",
                              "ts": time.time_ns() // 1000, "args": args})
        return
    _TRACER.instant(name, cat, args)


def counter(name: str, **values) -> None:
    if not _ENABLED:
        if flightrec._ENABLED:
            flightrec.record({"name": name, "ph": "C",
                              "ts": time.time_ns() // 1000, "args": values})
        return
    _TRACER.counter(name, values)


def set_status(**kv) -> None:
    """Attach extra fields to this host's heartbeat status — cap-utilization
    fractions and forecast advisories ride the next beat so tpu_watch
    --status can surface them.  No-op (one global check) when tracing is
    off: the heartbeat file only exists under an armed tracer."""
    if _TRACER is not None:
        _TRACER._status.update(kv)


def heartbeat_now() -> None:
    """Force an immediate (unthrottled) heartbeat write carrying the
    current status — the watchdog's fire path must land its wedged/
    recovering stamp before the process potentially exits."""
    if _TRACER is not None:
        _TRACER._beat.beat(_TRACER._status)
