"""Crash-surviving flight recorder: a bounded in-memory event ring.

The jsonl tracer answers "what happened" only when it was armed before the
run — but preemptions, SIGTERMs and fault-ladder rungs hit production runs
that fly with tracing off (the <2% overhead bound exists precisely so they
can).  The flight recorder closes that gap: a per-host ring of the last N
span/instant/counter events, fed from the tracer module's cheap disabled
path, dumped atomically to ``flightrec-host<N>.json`` when something goes
wrong (driver signal handler, fault-ladder rungs, injected preemptions,
tpu_watch wedge verdicts).  Post-mortems then start from the final seconds
of the run even when no tracer file exists.

Knobs (read by :func:`configure`, re-read at every run start):

* ``RDFIND_FLIGHTREC`` — "" / "0" off (default); "1" on (dumps land in the
  trace dir when tracing is armed, else the cwd); any other value is a
  directory path to dump into.
* ``RDFIND_FLIGHTREC_EVENTS`` — ring capacity (default 512).

Stdlib-only (the obs contract); the tracer imports this module, never the
reverse.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

DUMP_PREFIX = "flightrec-host"
DEFAULT_EVENTS = 512

# Module-level fast gate: the tracer's disabled path checks this attribute
# per event, so it must be a plain bool, not an env read.
_ENABLED = False
_DIR: str | None = None
_RING: collections.deque | None = None
_HOST = 0
_N_DROPPED = 0
_lock = threading.Lock()


def configure(host_index: int | None = None) -> bool:
    """(Re-)read the env knobs; returns whether recording is on.

    Called at import, at every driver run start, and by tests after
    flipping the env.  Reconfiguring keeps any already-recorded events
    that fit the (possibly new) capacity.
    """
    global _ENABLED, _DIR, _RING, _HOST
    raw = os.environ.get("RDFIND_FLIGHTREC", "")
    with _lock:
        if host_index is not None:
            _HOST = host_index
        if raw in ("", "0"):
            _ENABLED = False
            _DIR = None
            _RING = None
            return False
        _DIR = None if raw == "1" else raw
        try:
            cap = int(os.environ.get("RDFIND_FLIGHTREC_EVENTS",
                                     str(DEFAULT_EVENTS)))
        except ValueError:
            cap = DEFAULT_EVENTS
        cap = max(1, cap)
        if _RING is None or _RING.maxlen != cap:
            _RING = collections.deque(_RING or (), maxlen=cap)
        _ENABLED = True
        return True


def enabled() -> bool:
    return _ENABLED


def set_host(host_index: int) -> None:
    global _HOST
    _HOST = host_index


def record(ev: dict) -> None:
    """Append one event (deque.append is atomic; no lock on the hot path)."""
    ring = _RING
    if ring is not None:
        ring.append(ev)


def snapshot() -> list[dict]:
    ring = _RING
    return list(ring) if ring is not None else []


def reset() -> None:
    """Drop recorded events (run boundaries, tests); keeps configuration."""
    ring = _RING
    if ring is not None:
        ring.clear()


def dump_path(directory: str, host_index: int | None = None) -> str:
    h = _HOST if host_index is None else host_index
    return os.path.join(directory, f"{DUMP_PREFIX}{h}.json")


def dump(directory: str | None = None, reason: str = "",
         host_index: int | None = None) -> str | None:
    """Atomically write the ring to ``flightrec-host<N>.json``; returns the
    path, or None when recording is off.  Never raises (dump sites are
    signal handlers and exception paths) — a failed write is recorded as a
    dropped dump, not a crash."""
    if not _ENABLED:
        return None
    try:
        out_dir = directory or _DIR
        if out_dir is None:
            from . import tracer
            out_dir = tracer.trace_dir() or "."
        h = _HOST if host_index is None else host_index
        events = snapshot()
        payload = {"host": h, "reason": reason,
                   "dumped_at": round(time.time(), 3),
                   "n_events": len(events), "events": events}
        os.makedirs(out_dir, exist_ok=True)
        path = dump_path(out_dir, h)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        return path
    except Exception:
        global _N_DROPPED
        _N_DROPPED += 1
        return None


def load(path: str) -> dict | None:
    """Parse a dump file (tpu_watch --status, post-mortem tooling)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def find_dumps(directory: str) -> dict[int, str]:
    """{host_index: path} of every dump file in a directory."""
    out: dict[int, str] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(DUMP_PREFIX) and name.endswith(".json")):
            continue
        try:
            out[int(name[len(DUMP_PREFIX):-len(".json")])] = (
                os.path.join(directory, name))
        except ValueError:
            continue
    return out


configure()
