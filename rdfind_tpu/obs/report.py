"""Trace merging/export + the one formatter behind every counter report.

Two jobs live here:

* **Trace tooling.**  Each host's tracer writes ``events-host<N>.jsonl``;
  ``export_chrome_trace`` merges them into one Chrome-trace JSON (per-host
  lanes via the pid field, timestamps rebased to the earliest event) that
  loads in Perfetto (ui.perfetto.dev) or chrome://tracing.  Run standalone:
  ``python -m rdfind_tpu.obs.report TRACE_DIR``.  ``build_span_tree``
  reconstructs the span hierarchy from the B/E stream — the integrity
  check the obs tests pin (every open span closes, passes nest under
  stages).

* **Counter formatting.**  Before this module, the driver, bench.py and the
  tests each formatted dispatch/exchange/ingest counters their own way.
  ``format_debug_lines`` / ``format_counter_lines`` / ``format_timing_lines``
  are now the single rendering of the legacy stats keys (the key lists
  themselves live in obs/metrics.py).

Stdlib-only (the obs contract).
"""

from __future__ import annotations

import json
import os

from . import forecast, metrics
from .tracer import EVENTS_PREFIX

TRACE_FILE = "trace.json"


# ---------------------------------------------------------------------------
# Trace loading / merging / export.
# ---------------------------------------------------------------------------


def load_events(path: str) -> list[dict]:
    """Events from one per-host JSONL file (torn tail lines are skipped —
    a preempted run's file ends mid-write by design)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def host_event_files(trace_dir: str) -> dict[int, str]:
    """{host_index: path} of every per-host event file in the directory."""
    out = {}
    try:
        names = os.listdir(trace_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(EVENTS_PREFIX) and name.endswith(".jsonl")):
            continue
        try:
            h = int(name[len(EVENTS_PREFIX):-len(".jsonl")])
        except ValueError:
            continue
        out[h] = os.path.join(trace_dir, name)
    return out


def merge_traces(trace_dir: str) -> dict:
    """All hosts' events as one Chrome-trace object with per-host lanes.

    Every event's pid is forced to the host index from its FILE name (the
    authoritative lane assignment; a mislabeled event cannot jump lanes),
    timestamps are rebased to the earliest event across hosts, and each
    lane gets a process_name metadata record so Perfetto shows "host N".
    """
    events: list[dict] = []
    files = host_event_files(trace_dir)
    for h in sorted(files):
        for ev in load_events(files[h]):
            ev["pid"] = h
            events.append(ev)
    t0 = min((ev["ts"] for ev in events if "ts" in ev), default=0)
    for ev in events:
        if "ts" in ev:
            ev["ts"] = ev["ts"] - t0
    meta = [{"name": "process_name", "ph": "M", "pid": h, "tid": 0,
             "args": {"name": f"host {h}"}} for h in sorted(files)]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def export_chrome_trace(trace_dir: str, out_path: str | None = None) -> str:
    """Write the merged Chrome-trace JSON next to the event files."""
    trace = merge_traces(trace_dir)
    out_path = out_path or os.path.join(trace_dir, TRACE_FILE)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f, default=str)
    os.replace(tmp, out_path)
    return out_path


def build_span_tree(events: list[dict]) -> tuple[list[dict], list[dict]]:
    """(roots, unclosed) span trees from a B/E event stream.

    Spans nest per (pid, tid) lane in stream order.  Each node is
    {"name", "cat", "ts", "dur", "args", "children"}; `unclosed` lists
    spans whose E event never arrived (empty on a clean run — the span-tree
    integrity contract).
    """
    stacks: dict[tuple, list[dict]] = {}
    roots: list[dict] = []
    unclosed: list[dict] = []
    for ev in events:
        lane = (ev.get("pid"), ev.get("tid"))
        stack = stacks.setdefault(lane, [])
        ph = ev.get("ph")
        if ph == "B":
            node = {"name": ev.get("name"), "cat": ev.get("cat"),
                    "ts": ev.get("ts"), "dur": None,
                    "args": ev.get("args", {}), "children": []}
            (stack[-1]["children"] if stack else roots).append(node)
            stack.append(node)
        elif ph == "E":
            while stack:
                node = stack.pop()
                node["dur"] = ev.get("ts", node["ts"]) - node["ts"]
                if node["name"] == ev.get("name"):
                    break
        elif ph == "i":
            node = {"name": ev.get("name"), "cat": ev.get("cat"),
                    "ts": ev.get("ts"), "dur": 0,
                    "args": ev.get("args", {}), "children": []}
            (stack[-1]["children"] if stack else roots).append(node)
    for stack in stacks.values():
        unclosed.extend(stack)
    return roots, unclosed


def walk_spans(roots: list[dict]):
    """Depth-first (node, parent) pairs over a span forest."""
    todo = [(n, None) for n in roots]
    while todo:
        node, parent = todo.pop()
        yield node, parent
        todo.extend((c, node) for c in node["children"])


# ---------------------------------------------------------------------------
# The one counter formatter (--debug / -c / bench share these renderings).
# ---------------------------------------------------------------------------


def format_debug_lines(stats: dict) -> list[str]:
    """Every --debug stats line the driver prints, in fixed order, rendered
    from the canonical key groups in obs/metrics.py."""
    lines: list[str] = []
    ing = stats.get("ingest")
    if ing:
        # Parallel-ingest telemetry: phase split (worker phases are sums
        # across threads), throughput, and the consumer-side stall count.
        lines.append(
            f"ingest: threads={ing.get('n_threads')} "
            f"units={ing.get('n_units')} files={ing.get('n_files')} "
            f"bytes={ing.get('bytes_read')} "
            f"read_ms={ing.get('read_ms')} parse_ms={ing.get('parse_ms')} "
            f"intern_ms={ing.get('intern_ms')} "
            f"merge_ms={ing.get('merge_ms')} remap_ms={ing.get('remap_ms')} "
            f"stalls={ing.get('queue_stalls')} "
            f"triples/s={ing.get('triples_per_sec')} "
            f"bytes/s={ing.get('bytes_per_sec')}")
    if stats.get("exchange_sites"):
        # Per-exchange communication ledger: fixed-shape collective volume
        # per site, split by interconnect tier (intra-host ICI vs inter-host
        # DCN) — the input to multi-chip bandwidth projections.
        for site, e in sorted(stats["exchange_sites"].items()):
            # Timing keys exist only under RDFIND_COLLECTIVE_TIMING — the
            # suffix is additive so untimed runs render the historical line.
            timing = ""
            if "wall_ms" in e:
                timing = (f" wall_ms={e['wall_ms']} gbps={e.get('gbps', 0)} "
                          f"link_util={e.get('link_util', 0)}")
            lines.append(
                f"exchange[{site}]: calls={e['calls']} "
                f"capacity={e['capacity']} lanes={e['lanes']} "
                f"bytes={e['bytes']} ici_bytes={e.get('ici_bytes', 0)} "
                f"dcn_bytes={e.get('dcn_bytes', 0)} "
                f"reply_bytes={e.get('reply_bytes', 0)} "
                f"hier={e.get('hier', 0)} "
                f"rows_capacity={e['rows_capacity']} "
                f"overflow_retries={e['overflow_retries']}" + timing)
    if "overlap" in stats:
        # The overlap-efficiency row: where the measured wall sits between
        # the no-overlap and perfect-overlap bounds (dispatch.overlap_report).
        ov = stats["overlap"]
        lines.append(
            f"overlap: passes={ov['n_passes']} "
            f"measured_ms={ov['measured_ms']} pull_ms={ov['pull_ms']} "
            f"overlap_ms={ov['overlap_ms']} "
            f"serial_bound_ms={ov['serial_bound_ms']} "
            f"parallel_bound_ms={ov['parallel_bound_ms']} "
            f"efficiency={ov['overlap_efficiency']}")
    if "host_skew" in stats:
        # Straggler verdict: slowest host, how much slower, and which phase.
        hs = stats["host_skew"]
        lines.append(
            f"host skew: hosts={hs['n_hosts']} passes={hs['n_passes']} "
            f"skew_index={hs['skew_index']} "
            f"slowest_host={hs['slowest_host']} cause={hs['cause']}")
    if "dense_plan" in stats:
        # Dense cooc occupancy: the roofline-correcting record (issued vs
        # real FLOPs of the scheduled tile sweep) plus the resolved dtype.
        # The kernel_resolution struct (cooc.resolution_report) folds the
        # remaining kernel-mode decisions — emit-pipeline K schedule and
        # the actual plane element type — onto the same line, so every
        # plane-bits/emit/fuse choice reads in one place.
        dp = stats["dense_plan"]
        kr = stats.get("kernel_resolution", {})
        res = ""
        if kr:
            res = (f"kernel={kr.get('kernel_dtype')}"
                   f"/{kr.get('plane_elem')} "
                   f"emit={1 if kr.get('emit_pipeline') else 0} ")
        lines.append(
            f"dense plan: dtype={stats.get('cooc_dtype')} "
            f"policy={dp['policy']} "
            f"planes={dp.get('plane_bits', 8)}b " + res +
            f"fused={1 if dp.get('fuse_verdict') else 0} "
            f"lines={dp['l_real']}/{dp['l_pad']} "
            f"caps={dp['c_real']}/{dp['c_pad']} tile={dp['tile']} "
            f"tiles={dp['n_tiles'] - dp['n_tiles_skipped']}"
            f"/{dp['n_tiles']} "
            f"blocks_skipped={dp.get('n_blocks_skipped', 0)}"
            f"/{dp.get('n_blocks', 0)} occupancy={dp['occupancy']}")
    elif "cooc_dtype" in stats:
        kr = stats.get("kernel_resolution", {})
        if kr:
            lines.append(
                f"cooc dtype: {stats['cooc_dtype']} "
                f"planes={kr.get('plane_bits')}b "
                f"kernel={kr.get('kernel_dtype')}/{kr.get('plane_elem')} "
                f"emit={1 if kr.get('emit_pipeline') else 0} "
                f"fused={1 if kr.get('fuse_verdict') else 0}")
        else:
            lines.append(f"cooc dtype: {stats['cooc_dtype']}")
    if "n_host_syncs" in stats:
        # Dispatch telemetry of the pipelined pass executor: proof the
        # compute/readback overlap happened, not an assertion of it.
        lines.append(
            f"dispatch: passes={stats.get('n_pair_passes', 1)} "
            f"in_flight={stats.get('n_passes_in_flight', 1)} "
            f"host_syncs={stats['n_host_syncs']} "
            f"sync_ms={stats.get('host_sync_ms', 0.0):.1f} "
            f"overlap_ms={stats.get('pull_overlap_ms', 0.0):.1f} "
            f"cap_retries={stats.get('n_pair_cap_retries', 0)} "
            f"cap_p={stats.get('cap_p_final', 0)}")
    if stats.get("hbm"):
        hbm = stats["hbm"]
        lines.append(
            f"hbm: in_use={hbm.get('in_use_bytes')} "
            f"peak={hbm.get('peak_bytes')} limit={hbm.get('limit_bytes')} "
            f"frac={hbm.get('frac')} delta={hbm.get('delta_bytes')}")
    if stats.get("degradations"):
        # The degradation ledger: every ladder rung the run took instead of
        # dying (grow / split / skip / fallback), in order.
        for step in stats["degradations"]:
            lines.append(f"degradation: {step}")
        lines.append(f"ladder rungs: {stats.get('ladder_rung', {})}")
    if (stats.get("n_overflow_retries") or stats.get("n_host_pull_retries")
            or stats.get("resumed_passes")):
        lines.append(
            f"fault recovery: overflow_retries="
            f"{stats.get('n_overflow_retries', 0)} "
            f"host_pull_retries={stats.get('n_host_pull_retries', 0)} "
            f"backoff_ms={stats.get('backoff_ms_total', 0.0):.1f} "
            f"resumed_passes={stats.get('resumed_passes', 0)}")
    if stats.get("elastic_resume"):
        # Resume lineage: what mesh the snapshots came from, what got
        # re-sharded, and how the hosts agreed (models/sharded
        # _resolve_resume + the driver's preemption supervisor).
        er = stats["elastic_resume"]
        lines.append(
            f"elastic resume: from_dev={er.get('from_num_dev', '-')} "
            f"to_dev={er.get('to_num_dev', '-')} "
            f"resharded_blocks={er.get('resharded_blocks', 0)} "
            f"resharded_bytes={er.get('resharded_bytes', 0)} "
            f"vote_rounds={er.get('vote_rounds', 0)} "
            f"adopted_n_pass={er.get('adopted_n_pass', '-')} "
            f"supervisor_attempts={er.get('supervisor_attempts', 0)}")
    if stats.get("datastats_lines"):
        # The data plane: what the join-line / capture distributions looked
        # like (obs/datastats.py), not just what the machinery did to them.
        dl = stats["datastats_lines"]
        lines.append(
            f"datastats[lines]: n={dl.get('n_lines')} "
            f"max={dl.get('max_line')} giants={dl.get('giant_lines')} "
            f"giant_share={dl.get('giant_share')} "
            f"source={dl.get('source')}")
    if stats.get("datastats_captures"):
        dc = stats["datastats_captures"]
        lines.append(
            f"datastats[captures]: n={dc.get('n_captures')} "
            f"max_support={dc.get('max_support')} "
            f"source={dc.get('source')}")
    if stats.get("datastats_block_skip"):
        bs = stats["datastats_block_skip"]
        lines.append(
            f"datastats[block_skip]: skipped={bs.get('n_blocks_skipped')}"
            f"/{bs.get('n_blocks')} frac={bs.get('skip_frac')}")
    if stats.get("cap_utilization"):
        caps = " ".join(
            f"{cap}={row.get('used')}/{row.get('planned')}"
            f"({row.get('frac')})"
            for cap, row in sorted(stats["cap_utilization"].items()))
        lines.append(f"cap utilization: {caps}")
    # Forecast advisories render through the one shared formatter — the
    # --debug output and `report --summary` cannot drift apart.
    lines.extend(forecast.format_lines(stats))
    return lines


def summarize_passes(trace_dir: str) -> dict[int, dict]:
    """Per-host per-pass rows joined from the trace counter lanes.

    Reads each host's ``events-host<N>.jsonl`` and rebuilds the pass table
    the run printed live: one row per ``pass_phase_ms`` counter sample, the
    preceding ``host_skew`` sample attached to it (the skew meter emits
    skew, then phases, per committed pass), the matching
    ``cap_utilization`` sample joined on its own pass index, and every
    ``cap_forecast`` instant collected as advisories.  Returns
    {host: {"passes": [row...], "advisories": [adv...]}}.
    """
    out: dict[int, dict] = {}
    for h, path in sorted(host_event_files(trace_dir).items()):
        rows: list[dict] = []
        util_by_pass: dict[int, dict] = {}
        advisories: list[dict] = []
        pending_skew: dict | None = None
        for ev in load_events(path):
            name, ph = ev.get("name"), ev.get("ph")
            args = ev.get("args", {})
            if ph == "C" and name == "host_skew":
                pending_skew = args
            elif ph == "C" and name == "pass_phase_ms":
                row = {"pass": len(rows), "phase_ms": dict(args)}
                if pending_skew is not None:
                    row["skew"] = pending_skew.get("skew")
                    row["slowest"] = pending_skew.get("slowest")
                    pending_skew = None
                rows.append(row)
            elif ph == "C" and name == "cap_utilization":
                util_by_pass[args.get("pass")] = {
                    k: v for k, v in args.items() if k != "pass"}
            elif ph == "i" and name == "cap_forecast":
                advisories.append(dict(args))
        for row in rows:
            if row["pass"] in util_by_pass:
                row["cap_util"] = util_by_pass[row["pass"]]
        out[h] = {"passes": rows, "advisories": advisories}
    return out


def format_summary_lines(summary: dict[int, dict]) -> list[str]:
    """The `report --summary` rendering: one line per committed pass (total
    + phase split + host skew + cap-utilization fractions), then the
    forecast advisories through the shared advisory formatter."""
    lines: list[str] = []
    for h in sorted(summary):
        for row in summary[h]["passes"]:
            pm = row["phase_ms"]
            total = sum(v for v in pm.values()
                        if isinstance(v, (int, float)))
            phases = " ".join(f"{k}={v}" for k, v in pm.items())
            skew = (f" skew={row['skew']} slowest={row['slowest']}"
                    if row.get("skew") is not None else "")
            util = ""
            if row.get("cap_util"):
                util = " | util " + " ".join(
                    f"{k}={v}" for k, v in sorted(row["cap_util"].items()))
            lines.append(f"host {h} pass {row['pass']}: {total:.1f} ms "
                         f"({phases}){skew}{util}")
        for adv in summary[h]["advisories"]:
            lines.append(f"host {h} " + forecast.advisory_line(adv))
    if not lines:
        lines.append("no committed passes recorded (was the run traced "
                     "with --trace, and did it reach the pair passes?)")
    return lines


def format_counter_lines(counters: dict) -> list[str]:
    """The -c counter report (sorted `key: value` lines)."""
    return [f"{k}: {v}" for k, v in sorted(counters.items())]


def format_timing_lines(timings: dict, counters: dict | None = None) -> list[str]:
    """Phase wall-clock report + the machine-readable CSV line
    (AbstractFlinkProgram.java:149-182)."""
    total = sum(timings.values())
    lines = [f"phase {name}: {secs * 1000:.1f} ms"
             for name, secs in timings.items()]
    lines.append(f"total: {total * 1000:.1f} ms")
    counters = counters or {}
    csv = ",".join([f"{timings.get(k, 0.0) * 1000:.0f}"
                    for k in ("read+parse", "intern", "discover")]
                   + [f"{total * 1000:.0f}",
                      str(counters.get("cind-counter", 0))])
    lines.append(f"csv:{csv}")
    return lines


def dispatch_row(stats: dict) -> dict:
    """The dispatch+fault telemetry row bench.py embeds per mode — built
    from the canonical key groups so bench, driver and tests cannot drift."""
    return {k: stats.get(k)
            for k in metrics.DISPATCH_KEYS + metrics.FAULT_KEYS[:3]}


def kernel_feed_stall_fraction(host_skew: dict | None) -> float | None:
    """Kernel-feed stall fraction: exchange-wait ms ÷ dense-compute ms.

    Derived from the _SkewMeter phase vectors (stats["host_skew"]
    ["phase_ms"], per-host totals over the committed passes): the fraction
    of the dense compute wall the exchange machinery spends feeding it.
    0.1 means the exchange costs 10% of the kernel time it feeds — the
    PR-8 hierarchical exchange "can feed the kernel"; >= 1.0 means the
    sweep is exchange-bound and more chips will not help until the feed
    path improves.  Summed across hosts so multi-host skew does not hide
    in a mean.  None when the meter never armed (no obs consumer) or no
    compute was recorded — callers must treat absence as "not measured",
    never as 0 (a genuinely stall-free run reports 0.0, not None)."""
    phases = (host_skew or {}).get("phase_ms") or {}
    exchange = phases.get("exchange")
    compute = phases.get("compute")
    if not exchange or not compute:
        return None
    compute_ms = float(sum(compute))
    if compute_ms <= 0:
        return None
    return round(float(sum(exchange)) / compute_ms, 4)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m rdfind_tpu.obs.report",
        description="Merge per-host trace event files into one Chrome-trace "
                    "JSON (open in Perfetto: ui.perfetto.dev).")
    ap.add_argument("trace_dir", help="directory holding events-host*.jsonl")
    ap.add_argument("-o", "--output", default=None,
                    help="output path (default: TRACE_DIR/trace.json)")
    ap.add_argument("--summary", action="store_true",
                    help="print the per-pass table (phase ms, host skew, "
                         "cap utilization, forecast advisories) instead of "
                         "exporting the Chrome trace")
    args = ap.parse_args(argv)
    files = host_event_files(args.trace_dir)
    if not files:
        print(f"no {EVENTS_PREFIX}*.jsonl files in {args.trace_dir}")
        return 1
    if args.summary:
        for line in format_summary_lines(summarize_passes(args.trace_dir)):
            print(line)
        return 0
    out = export_chrome_trace(args.trace_dir, args.output)
    n = sum(len(load_events(p)) for p in files.values())
    print(f"wrote {out} ({len(files)} host lane(s), {n} events)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
