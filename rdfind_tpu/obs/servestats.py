"""Per-request serving telemetry + the freshness/SLO engine (ISSUE 20).

The PR-5 registry takes an RLock per observation — fine for a discovery
run's per-pass cadence, fatal on a query plane answering 100k+ QPS.  This
module is the serving process's hot-path telemetry: every request lands in
a **per-thread shard** (plain dict increments under the GIL — no lock, no
allocation beyond the first touch), and the shards are merged only when a
scrape, the /slo endpoint, or the SLO engine asks.  What it records, per
endpoint (``holds``/``referenced``/``topk``) × outcome
(``ok``/``400``/``503``/``refused``):

  * request counters (Prometheus: ``rdfind_serve_requests_total``);
  * log2^0.25-bucketed latency histograms for ok answers, bit-identical to
    the registry's bucketing (metrics.hist_bucket), with p50/p95/p99
    derived at exposition time — never on the query path;
  * a bounded slow-query ring (the flightrec idiom: a deque whose append
    is atomic) holding args + latency + generation of every query slower
    than ``RDFIND_SERVE_OBS_SLOW_US``, served at ``/debug/slowlog`` and
    dumped to ``slowlog-host<N>.json`` on SIGTERM.

The SLO engine evaluates three targets over the sharded counters:

  * ``RDFIND_SLO_P99_US``   — ok-answer p99 latency ceiling;
  * ``RDFIND_SLO_ERROR_FRAC`` — non-200 fraction ceiling;
  * ``RDFIND_SLO_STALENESS_S`` — freshness ceiling (IndexService's
    bundle-commit → serving-swap lag, live-growing while a swap is
    pending or refused).

Rate targets use two burn windows (``RDFIND_SLO_FAST_S`` /
``RDFIND_SLO_SLOW_S``): **burning** means both windows exceed the target
(a sustained burn — pageable), one window alone is **warn** (a spike or a
tail still draining — visible, not pageable), which is what keeps a
flapping error burst from paging.  Windows are diffs between cumulative
snapshots the engine keeps itself; an empty window or a skewed clock
(snapshot from the future) yields no verdict rather than a false one.
The verdict is named — {"state", "slo"} — and lands on the heartbeat,
``/status``, and ``/slo``.

``RDFIND_SERVE_OBS=0`` disables recording entirely; answers are
bit-identical either way (recording never touches the payload), which
bench_serve.py and scripts/serve_obs_parity.py assert.

Stdlib-only (the obs contract).
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time

from . import metrics

ENDPOINTS = ("holds", "referenced", "topk")
OUTCOMES = ("ok", "400", "503", "refused")

DEFAULT_SLOW_US = 10_000.0
DEFAULT_SLOWLOG_EVENTS = 64
DEFAULT_FAST_S = 60.0
DEFAULT_SLOW_S = 600.0
SLOWLOG_PREFIX = "slowlog-host"

# Module-level fast gate (the flightrec idiom): the query path checks a
# plain bool, never the environment.
_ENABLED = True
_SLOW_US = DEFAULT_SLOW_US
_SLOWLOG: collections.deque = collections.deque(
    maxlen=DEFAULT_SLOWLOG_EVENTS)
_HOST = 0

# Shard registry: the lock guards only shard creation and the scrape-side
# list copy — never a record() call.  _EPOCH invalidates thread-local
# shards across reset() so a long-lived handler thread re-registers.
_SHARDS: list["_Shard"] = []
_SHARDS_LOCK = threading.Lock()
_EPOCH = 0
_TLS = threading.local()


class _Shard:
    """One thread's private counters: (endpoint, outcome) -> count, and
    endpoint -> [count, total_us, min_us, max_us, {bucket: count}]."""

    __slots__ = ("epoch", "counts", "lat")

    def __init__(self, epoch: int):
        self.epoch = epoch
        self.counts: dict = {}
        self.lat: dict = {}


def configure(host_index: int | None = None) -> bool:
    """(Re-)read the env knobs; returns whether recording is on.  Called at
    import, by the serving process at startup, and by tests/benches after
    flipping RDFIND_SERVE_OBS."""
    global _ENABLED, _SLOW_US, _SLOWLOG, _HOST
    if host_index is not None:
        _HOST = int(host_index)
    _ENABLED = os.environ.get("RDFIND_SERVE_OBS", "").strip() != "0"
    try:
        _SLOW_US = max(0.0, float(
            os.environ.get("RDFIND_SERVE_OBS_SLOW_US", "")
            or DEFAULT_SLOW_US))
    except ValueError:
        _SLOW_US = DEFAULT_SLOW_US
    try:
        cap = int(os.environ.get("RDFIND_SERVE_OBS_SLOWLOG", "")
                  or DEFAULT_SLOWLOG_EVENTS)
    except ValueError:
        cap = DEFAULT_SLOWLOG_EVENTS
    cap = max(1, cap)
    if _SLOWLOG.maxlen != cap:
        _SLOWLOG = collections.deque(_SLOWLOG, maxlen=cap)
    return _ENABLED


def enabled() -> bool:
    return _ENABLED


def reset() -> None:
    """Drop every shard, the slowlog, and the SLO engine's history (run
    boundaries, tests).  Live threads re-register on their next record."""
    global _EPOCH, _ENGINE
    with _SHARDS_LOCK:
        _EPOCH += 1
        _SHARDS.clear()
    _SLOWLOG.clear()
    _ENGINE = None


def _shard() -> _Shard:
    s = getattr(_TLS, "shard", None)
    if s is None or s.epoch != _EPOCH:
        s = _TLS.shard = _Shard(_EPOCH)
        with _SHARDS_LOCK:
            if s.epoch == _EPOCH:
                _SHARDS.append(s)
    return s


def record(endpoint: str, outcome: str, us: float | None = None,
           generation=None, args=None) -> None:
    """The hot path: one dict increment (plus one histogram increment and
    a min/max fold for ok answers).  No lock, no registry, no allocation
    after a thread's first touch."""
    if not _ENABLED:
        return
    s = _shard()
    key = (endpoint, outcome)
    s.counts[key] = s.counts.get(key, 0) + 1
    if us is None:
        return
    lat = s.lat.get(endpoint)
    if lat is None:
        lat = s.lat[endpoint] = [0, 0.0, math.inf, -math.inf, {}]
    b = metrics.hist_bucket(us)
    buckets = lat[4]
    buckets[b] = buckets.get(b, 0) + 1
    lat[0] += 1
    lat[1] += us
    if us < lat[2]:
        lat[2] = us
    if us > lat[3]:
        lat[3] = us
    if us >= _SLOW_US:
        # deque.append is atomic; the ring bounds itself.
        _SLOWLOG.append({"endpoint": endpoint, "us": round(us, 1),
                         "generation": generation, "args": args,
                         "ts": round(time.time(), 3)})


# ---------------------------------------------------------------------------
# Scrape-side aggregation (merges the shards; holds only _SHARDS_LOCK for
# the list copy — concurrent record() calls keep landing while we read).
# ---------------------------------------------------------------------------


def _merged() -> tuple[dict, dict]:
    """(counts, lat): counts maps (endpoint, outcome) -> n; lat maps
    endpoint -> [total_us, min_us, max_us, {bucket: count}].  Histogram
    counts are derived from the bucket sums, so a scrape racing a storm is
    internally consistent (count == sum(buckets)), never torn."""
    with _SHARDS_LOCK:
        shards = list(_SHARDS)
    counts: dict = {}
    lat: dict = {}
    for s in shards:
        for k, v in list(s.counts.items()):
            counts[k] = counts.get(k, 0) + v
        for ep, row in list(s.lat.items()):
            agg = lat.setdefault(ep, [0.0, math.inf, -math.inf, {}])
            agg[0] += row[1]
            agg[1] = min(agg[1], row[2])
            agg[2] = max(agg[2], row[3])
            for b, n in list(row[4].items()):
                agg[3][b] = agg[3].get(b, 0) + n
    return counts, lat


def aggregate() -> dict:
    """The merged view: per endpoint×outcome request counters, per-endpoint
    latency summaries with exposition-time p50/p95/p99, and the total /
    error fraction the SLO engine burns against."""
    counts, lat = _merged()
    requests: dict = {}
    total = errors = 0
    for (ep, oc), n in counts.items():
        requests.setdefault(ep, {})[oc] = (
            requests.get(ep, {}).get(oc, 0) + n)
        total += n
        if oc != "ok":
            errors += n
    latency: dict = {}
    for ep, (tot, mn, mx, buckets) in sorted(lat.items()):
        n = sum(buckets.values())
        if not n:
            continue
        row = {"count": n, "sum": round(tot, 3),
               "min": round(mn, 3), "max": round(mx, 3),
               "mean": round(tot / n, 3)}
        for q in metrics.QUANTILES:
            v = metrics.bucket_quantile(buckets, q, vmin=mn, vmax=mx)
            row[f"p{int(q * 100)}"] = round(v, 3) if v is not None else None
        latency[ep] = row
    return {"enabled": _ENABLED, "requests": requests,
            "latency_us": latency, "total": total, "errors": errors,
            "error_frac": round(errors / total, 6) if total else 0.0}


def prometheus_text(prefix: str = "rdfind_") -> str:
    """Prometheus text exposition of the sharded stats (appended to the
    registry's exposition by the serve console's /metrics)."""
    counts, lat = _merged()
    lines: list[str] = []
    name = f"{prefix}serve_requests_total"
    lines.append(f"# TYPE {name} counter")
    for (ep, oc) in sorted(counts):
        lines.append(f'{name}{{endpoint="{ep}",outcome="{oc}"}} '
                     f"{counts[(ep, oc)]}")
    for ep in sorted(lat):
        tot, mn, mx, buckets = lat[ep]
        n = sum(buckets.values())
        base = f"{prefix}serve_{ep}_latency_us"
        lines.append(f"# TYPE {base} summary")
        for q in metrics.QUANTILES:
            v = metrics.bucket_quantile(buckets, q, vmin=mn, vmax=mx)
            if v is not None:
                lines.append(f'{base}{{quantile="{q}"}} {v}')
        lines.append(f"{base}_count {n}")
        lines.append(f"{base}_sum {tot}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Slow-query ring.
# ---------------------------------------------------------------------------


def slow_us() -> float:
    return _SLOW_US


def slowlog() -> list[dict]:
    """The ring's contents, oldest first (/debug/slowlog)."""
    return list(_SLOWLOG)


def dump_path(directory: str, host_index: int | None = None) -> str:
    h = _HOST if host_index is None else host_index
    return os.path.join(directory, f"{SLOWLOG_PREFIX}{h}.json")


def dump_slowlog(directory: str | None = None, reason: str = "") -> str | None:
    """Atomically write the slow-query ring (SIGTERM / shutdown path).
    Never raises — dump sites are signal handlers."""
    if not _ENABLED:
        return None
    try:
        out_dir = directory or "."
        entries = slowlog()
        payload = {"host": _HOST, "reason": reason,
                   "dumped_at": round(time.time(), 3),
                   "slow_us": _SLOW_US,
                   "n_entries": len(entries), "entries": entries}
        os.makedirs(out_dir, exist_ok=True)
        path = dump_path(out_dir)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


# ---------------------------------------------------------------------------
# The SLO engine: fast/slow burn windows over the sharded counters.
# ---------------------------------------------------------------------------


def _env_float(name: str, default: float) -> float:
    try:
        v = os.environ.get(name, "").strip()
        return float(v) if v else default
    except ValueError:
        return default


class SloEngine:
    """Named SLO verdicts from cumulative snapshots of the shard merge.

    ``evaluate()`` takes one snapshot (throttled), then compares the
    current totals against the snapshot nearest each window's start.  A
    target is **burning** only when the fast AND slow windows both exceed
    it; one window alone is **warn**.  Staleness is a level, not a rate:
    it burns when the server is generations behind for longer than the
    target allows.  Thresholds <= 0 disable their target."""

    def __init__(self, p99_us: float | None = None,
                 error_frac: float | None = None,
                 staleness_s: float | None = None,
                 fast_s: float | None = None, slow_s: float | None = None):
        self.p99_us = (_env_float("RDFIND_SLO_P99_US", 0.0)
                       if p99_us is None else float(p99_us))
        self.error_frac = (_env_float("RDFIND_SLO_ERROR_FRAC", 0.0)
                           if error_frac is None else float(error_frac))
        self.staleness_s = (_env_float("RDFIND_SLO_STALENESS_S", 0.0)
                            if staleness_s is None else float(staleness_s))
        self.fast_s = max(1.0, _env_float("RDFIND_SLO_FAST_S",
                                          DEFAULT_FAST_S)
                          if fast_s is None else float(fast_s))
        self.slow_s = max(self.fast_s,
                          _env_float("RDFIND_SLO_SLOW_S", DEFAULT_SLOW_S)
                          if slow_s is None else float(slow_s))
        # (ts, total, errors, {bucket: count}) cumulative snapshots.
        self.history: collections.deque = collections.deque(maxlen=1024)
        self.last: dict | None = None

    def config(self) -> dict:
        return {"p99_us": self.p99_us, "error_frac": self.error_frac,
                "staleness_s": self.staleness_s,
                "fast_s": self.fast_s, "slow_s": self.slow_s}

    # -- snapshots -----------------------------------------------------------

    @staticmethod
    def _snap(now: float) -> tuple:
        counts, lat = _merged()
        total = sum(counts.values())
        errors = sum(n for (ep, oc), n in counts.items() if oc != "ok")
        buckets: dict = {}
        for ep, row in lat.items():
            for b, n in row[3].items():
                buckets[b] = buckets.get(b, 0) + n
        return (now, total, errors, buckets)

    def observe_snapshot(self, now: float | None = None,
                         snap: tuple | None = None) -> None:
        """Append one cumulative snapshot (throttled to >= 0.5s spacing;
        snapshots from a skewed — backwards — clock are dropped)."""
        now = time.time() if now is None else now
        snap = self._snap(now) if snap is None else snap
        if self.history and now - self.history[-1][0] < 0.5:
            return
        if self.history and now < self.history[-1][0]:
            return  # clock went backwards; never record a negative window
        self.history.append(snap)

    def _window(self, cur: tuple, now: float, w: float) -> tuple | None:
        """The cumulative diff over the trailing `w` seconds: (dt, total,
        errors, {bucket: count}), or None when the window is empty.  A
        history shorter than the window bootstraps from its oldest
        snapshot (a young server's "slow window" is its whole life)."""
        base = None
        for s in self.history:
            if s[0] > now:
                continue  # future snapshot (clock skew): unusable
            if s[0] <= now - w:
                base = s  # newest snapshot at/before the window start
            elif base is None:
                base = s  # bootstrap: oldest usable snapshot
                break
            else:
                break
        if base is None:
            return None
        dt = now - base[0]
        total = cur[1] - base[1]
        if dt <= 0 or total <= 0:
            return None
        errors = cur[2] - base[2]
        buckets = {b: n - base[3].get(b, 0)
                   for b, n in cur[3].items()
                   if n - base[3].get(b, 0) > 0}
        return (dt, total, errors, buckets)

    # -- the verdict ---------------------------------------------------------

    def evaluate(self, freshness: dict | None = None,
                 now: float | None = None) -> dict:
        """The named verdict: {"state": ok|warn|burning, "slo": name|None,
        "detail": {...}}.  Worst target wins; burning beats warn."""
        now = time.time() if now is None else now
        cur = self._snap(now)
        self.observe_snapshot(now=now, snap=cur)
        fast = self._window(cur, now, self.fast_s)
        slow = self._window(cur, now, self.slow_s)
        verdicts: list[tuple[str, str, dict]] = []

        if self.error_frac > 0:
            f_frac = fast[2] / fast[1] if fast else None
            s_frac = slow[2] / slow[1] if slow else None
            f_over = f_frac is not None and f_frac > self.error_frac
            s_over = s_frac is not None and s_frac > self.error_frac
            detail = {"fast_frac": round(f_frac, 6) if f_frac is not None
                      else None,
                      "slow_frac": round(s_frac, 6) if s_frac is not None
                      else None, "target": self.error_frac}
            if f_over and s_over:
                verdicts.append(("burning", "error_frac", detail))
            elif f_over or s_over:
                verdicts.append(("warn", "error_frac", detail))

        if self.p99_us > 0:
            f_p99 = (metrics.bucket_quantile(fast[3], 0.99)
                     if fast and fast[3] else None)
            s_p99 = (metrics.bucket_quantile(slow[3], 0.99)
                     if slow and slow[3] else None)
            f_over = f_p99 is not None and f_p99 > self.p99_us
            s_over = s_p99 is not None and s_p99 > self.p99_us
            detail = {"fast_p99_us": round(f_p99, 1) if f_p99 is not None
                      else None,
                      "slow_p99_us": round(s_p99, 1) if s_p99 is not None
                      else None, "target_us": self.p99_us}
            if f_over and s_over:
                verdicts.append(("burning", "p99", detail))
            elif f_over or s_over:
                verdicts.append(("warn", "p99", detail))

        if self.staleness_s > 0 and freshness:
            behind = int(freshness.get("generations_behind") or 0)
            stale = freshness.get("staleness_s")
            detail = {"staleness_s": stale, "generations_behind": behind,
                      "target_s": self.staleness_s}
            if behind > 0 and stale is not None \
                    and stale > self.staleness_s:
                verdicts.append(("burning", "staleness", detail))
            elif behind > 0 or (stale is not None
                                and stale > self.staleness_s):
                verdicts.append(("warn", "staleness", detail))

        state, slo, detail = "ok", None, {}
        for st, name, d in verdicts:
            if st == "burning" and state != "burning":
                state, slo, detail = st, name, d
            elif st == "warn" and state == "ok":
                state, slo, detail = st, name, d
        out = {"state": state, "slo": slo, "detail": detail,
               "windows": {"fast_s": self.fast_s, "slow_s": self.slow_s},
               "evaluated_unix": round(now, 3)}
        self.last = out
        return out


_ENGINE: SloEngine | None = None


def slo_engine() -> SloEngine:
    """The process-wide engine (created from the env on first use)."""
    global _ENGINE
    if _ENGINE is None:
        _ENGINE = SloEngine()
    return _ENGINE


def evaluate_slo(freshness: dict | None = None,
                 now: float | None = None) -> dict:
    return slo_engine().evaluate(freshness=freshness, now=now)


def slo_config() -> dict:
    return slo_engine().config()


configure()
