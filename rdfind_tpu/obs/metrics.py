"""The metrics registry + the sanctioned publish shims.

Telemetry grew one dict at a time (DispatchStats keys in PR 1, fault-ladder
counters in PR 3, the exchange ledger and 12-lane ingest stats in PR 4),
each site assigning straight into a per-run ``stats`` dict.  The shims here
are now the ONLY sanctioned way to write telemetry (tests/test_obs_guard.py
greps for direct writes): each shim applies the identical mutation to the
caller's legacy ``stats`` dict AND to the process-wide registry mirror, so

  * every pre-existing ``stats`` key keeps its exact value and semantics
    (``Registry.snapshot()`` reproduces them bit-for-bit — differentially
    tested across all four sharded strategies), and
  * the registry can serve consumers the per-run dicts never could:
    Prometheus text exposition to a file, typed histograms, and the bench
    artifact's unified obs snapshot.

Stdlib-only at import time (the obs contract; runtime/faults.py imports
this module).
"""

from __future__ import annotations

import math
import os
import threading

COUNTER = "counter"
GAUGE = "gauge"
STRUCT = "struct"

# Canonical key groups shared by the --debug formatter (obs/report.py),
# bench.py's JSON rows, and the tests — the "identical key names" contract.
DISPATCH_KEYS = ("n_pair_passes", "n_passes_in_flight", "n_host_syncs",
                 "host_sync_ms", "pull_overlap_ms", "n_pair_cap_retries",
                 "cap_p_final")
FAULT_KEYS = ("n_overflow_retries", "n_host_pull_retries", "backoff_ms_total",
              "resumed_passes")
INGEST_KEYS = ("n_threads", "n_units", "n_files", "bytes_read", "read_ms",
               "parse_ms", "intern_ms", "merge_ms", "remap_ms",
               "queue_stalls", "triples_per_sec", "bytes_per_sec")
EXCHANGE_SITE_KEYS = ("calls", "capacity", "lanes", "bytes", "rows_capacity",
                      "overflow_retries")
MEMORY_KEYS = ("in_use_bytes", "peak_bytes", "limit_bytes", "frac",
               "delta_bytes")


# Log-bucketed quantile resolution: each bucket spans a ~19% value range
# (2**0.25), so a reported p50/p95/p99 is within ~10% of the true sample —
# plenty for latency attribution, at O(distinct magnitudes) memory.
_HIST_BASE = 2.0 ** 0.25
_HIST_LOG_BASE = math.log(_HIST_BASE)
_HIST_UNDERFLOW = -(1 << 30)  # single bucket for values <= 0
QUANTILES = (0.5, 0.95, 0.99)


def hist_bucket(value: float) -> int:
    """The log-bucket index ``Histogram.observe`` files `value` under.
    Shared with the serving stats shards (obs/servestats.py) so their
    scrape-time quantiles use bit-identical bucketing."""
    if value > 0.0:
        return math.floor(math.log(value) / _HIST_LOG_BASE)
    return _HIST_UNDERFLOW


def bucket_quantile(buckets: dict, q: float, vmin: float | None = None,
                    vmax: float | None = None) -> float | None:
    """Approximate q-quantile of a {bucket_index: count} map (bucket
    midpoint, clamped to [vmin, vmax] when given).  The merge-side twin of
    ``Histogram.quantile`` for histograms aggregated across shards."""
    count = sum(buckets.values())
    if not count:
        return None
    rank = q * (count - 1)
    acc = 0
    for b in sorted(buckets):
        acc += buckets[b]
        if acc > rank:
            if b == _HIST_UNDERFLOW:
                return vmin if vmin is not None else 0.0
            mid = (_HIST_BASE ** b + _HIST_BASE ** (b + 1)) / 2.0
            if vmin is not None:
                mid = max(mid, vmin)
            if vmax is not None:
                mid = min(mid, vmax)
            return mid
    return vmax if vmax is not None else None


class Histogram:
    """Fixed-size summary of an observation stream (no per-sample storage);
    sparse log buckets give approximate quantiles."""

    __slots__ = ("count", "total", "min", "max", "_buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._buckets: dict[int, int] = {}

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        b = hist_bucket(value)
        self._buckets[b] = self._buckets.get(b, 0) + 1

    def quantile(self, q: float) -> float | None:
        """Approximate q-quantile from the log buckets (bucket midpoint,
        clamped to the observed [min, max] so p99 never exceeds max)."""
        if not self.count:
            return None
        rank = q * (self.count - 1)
        acc = 0
        for b in sorted(self._buckets):
            acc += self._buckets[b]
            if acc > rank:
                if b == _HIST_UNDERFLOW:
                    return self.min
                mid = (_HIST_BASE ** b + _HIST_BASE ** (b + 1)) / 2.0
                return min(max(mid, self.min), self.max)
        return self.max

    def describe(self) -> dict:
        if not self.count:
            return {"count": 0}
        out = {"count": self.count, "sum": round(self.total, 3),
               "min": round(self.min, 3), "max": round(self.max, 3),
               "mean": round(self.total / self.count, 3)}
        for q in QUANTILES:
            out[f"p{int(q * 100)}"] = round(self.quantile(q), 3)
        return out


class Registry:
    """The process-wide mirror of every shim-published stats key, plus the
    registry-only instruments (histograms)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._values: dict = {}
        self._kinds: dict[str, str] = {}
        self._hists: dict[str, Histogram] = {}

    def apply(self, fn, key: str | None = None, kind: str | None = None):
        with self._lock:
            if key is not None and kind is not None:
                self._kinds.setdefault(key, kind)
            fn(self._values)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists.setdefault(name, Histogram()).observe(value)

    def get(self, key: str, default=None):
        with self._lock:
            return self._values.get(key, default)

    def reset(self) -> None:
        with self._lock:
            self._values.clear()
            self._kinds.clear()
            self._hists.clear()

    def snapshot(self, jsonable: bool = False) -> dict:
        """Every mirrored stats key (bit-identical to the legacy dicts'
        values) plus histogram summaries under "histograms".

        jsonable=True drops values with no JSON form (numpy rule tables)
        and deep-copies the rest, for embedding in bench artifacts.
        """
        with self._lock:
            if not jsonable:
                out = dict(self._values)
            else:
                out = {}
                for k, v in self._values.items():
                    enc = _jsonable(v)
                    if enc is not None:
                        out[k] = enc
            if self._hists:
                out["histograms"] = {n: h.describe()
                                     for n, h in self._hists.items()}
            return out

    # -- Prometheus text exposition -----------------------------------------

    def prometheus_text(self, prefix: str = "rdfind_") -> str:
        lines: list[str] = []
        with self._lock:
            for key in sorted(self._values):
                value = self._values[key]
                kind = self._kinds.get(key, GAUGE)
                _prom_emit(lines, prefix, key, value, kind)
            for name in sorted(self._hists):
                h = self._hists[name]
                base = prefix + _prom_name(name)
                lines.append(f"# TYPE {base} summary")
                for q in QUANTILES:
                    v = h.quantile(q)
                    if v is not None:
                        lines.append(f'{base}{{quantile="{q}"}} {v}')
                lines.append(f"{base}_count {h.count}")
                lines.append(f"{base}_sum {h.total}")
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path: str) -> None:
        """Atomic exposition write (a scraper never reads a torn file)."""
        text = self.prometheus_text()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)


def _jsonable(v):
    """JSON-ready copy of a telemetry value, or None when it has none.
    (Mirrors runtime/checkpoint._jsonable, restated here so obs stays
    import-light and dependency-free of the checkpoint codecs.)"""
    if isinstance(v, (bool, str)):
        return v
    if isinstance(v, int):
        return int(v)
    if isinstance(v, float):
        return float(v)
    # numpy scalars quack like their Python types.
    for proto, cast in ((int, int), (float, float)):
        try:
            if hasattr(v, "item") and isinstance(v.item(), proto):
                return cast(v.item())
        except Exception:
            break
    if isinstance(v, dict):
        out = {}
        for k, x in v.items():
            enc = _jsonable(x)
            if enc is None:
                return None
            out[str(k)] = enc
        return out
    if isinstance(v, (list, tuple)):
        out = [_jsonable(x) for x in v]
        return None if any(x is None for x in out) else out
    return None


def _prom_name(key: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in key)


def _prom_emit(lines: list, prefix: str, key: str, value, kind: str,
               labels: str = "") -> None:
    """Numeric leaves become samples; one level of dict nesting becomes a
    label (site=/field=); strings and deeper structures are skipped."""
    if isinstance(value, bool):
        value = int(value)
    if isinstance(value, (int, float)):
        name = prefix + _prom_name(key)
        if not labels:
            lines.append(f"# TYPE {name} {kind if kind != STRUCT else GAUGE}")
        lines.append(f"{name}{labels} {value}")
        return
    if isinstance(value, dict) and not labels:
        for sub in sorted(value, key=str):
            v = value[sub]
            if isinstance(v, dict):
                # e.g. exchange_sites: {site: {calls: ..}} -> per-field rows.
                for field in sorted(v, key=str):
                    _prom_emit(lines, prefix, f"{key}_{field}", v[field],
                               GAUGE, labels=f'{{key="{sub}"}}')
            else:
                _prom_emit(lines, prefix, key, v, GAUGE,
                           labels=f'{{key="{sub}"}}')
    elif isinstance(value, list):
        lines.append(f"{prefix}{_prom_name(key)}_total {len(value)}")


_REGISTRY = Registry()
_EXPORT_PATH: str | None = None


def registry() -> Registry:
    return _REGISTRY


def reset() -> None:
    """Clear the process-wide mirror (run boundaries, tests)."""
    _REGISTRY.reset()


def set_export(path: str | None) -> None:
    """Arm (or disarm) Prometheus file exposition for this process."""
    global _EXPORT_PATH
    _EXPORT_PATH = path


def export_requested() -> bool:
    return _EXPORT_PATH is not None


def export_path() -> str | None:
    return _EXPORT_PATH


def flush_export() -> None:
    """Write the exposition file if armed (driver: run end + stage ends)."""
    if _EXPORT_PATH is not None:
        _REGISTRY.write_prometheus(_EXPORT_PATH)


# ---------------------------------------------------------------------------
# The sanctioned publish shims.  Every shim applies ONE mutation function to
# both containers, so the legacy dict and the registry mirror can never
# disagree on a key they both hold.
# ---------------------------------------------------------------------------


def mutate(stats: dict | None, fn, key: str | None = None,
           kind: str | None = None) -> None:
    """The root shim: apply `fn(container)` to the caller's stats dict (when
    given) and to the registry mirror.  `fn` must derive everything it
    writes from its own captures, reading the container only for
    accumulation — the two containers may hold different histories."""
    if stats is not None:
        fn(stats)
    _REGISTRY.apply(fn, key=key, kind=kind)


def counter_add(stats: dict | None, key: str, n=1) -> None:
    def fn(c):
        c[key] = c.get(key, 0) + n
    mutate(stats, fn, key=key, kind=COUNTER)


def counter_max(stats: dict | None, key: str, v) -> None:
    def fn(c):
        c[key] = max(c.get(key, 0), v)
    mutate(stats, fn, key=key, kind=GAUGE)


def time_add(stats: dict | None, key: str, ms: float, ndigits: int = 3) -> None:
    """Accumulate a duration in ms with the legacy round-to-3 convention."""
    def fn(c):
        c[key] = round(c.get(key, 0.0) + ms, ndigits)
    mutate(stats, fn, key=key, kind=COUNTER)


def gauge_set(stats: dict | None, key: str, v) -> None:
    def fn(c):
        c[key] = v
    mutate(stats, fn, key=key, kind=GAUGE)


def set_many(stats: dict | None, **kv) -> None:
    """The stats.update(...) shim (a batch of gauge assignments)."""
    def fn(c):
        c.update(kv)
    mutate(stats, fn)
    for k in kv:
        _REGISTRY._kinds.setdefault(k, GAUGE)


def struct_set(stats: dict | None, key: str, value) -> None:
    """Structured gauge (dense_plan, planned_caps, ingest, rebalance, ...)."""
    def fn(c):
        c[key] = value
    mutate(stats, fn, key=key, kind=STRUCT)


def struct_update(stats: dict | None, key: str, **kv) -> None:
    def fn(c):
        c.setdefault(key, {}).update(kv)
    mutate(stats, fn, key=key, kind=STRUCT)


def list_append(stats: dict | None, key: str, entry) -> None:
    def fn(c):
        c.setdefault(key, []).append(entry)
    mutate(stats, fn, key=key, kind=STRUCT)


def mapping_set(stats: dict | None, key: str, subkey, value) -> None:
    def fn(c):
        c.setdefault(key, {})[subkey] = value
    mutate(stats, fn, key=key, kind=STRUCT)


def restore(stats: dict | None, decoded: dict) -> None:
    """Re-publish a decoded stats dict (checkpoint resume): the resumed run
    must report the same stat-* counters as the run that produced it."""
    def fn(c):
        c.update(decoded)
    mutate(stats, fn)


def observe(name: str, value: float) -> None:
    """Registry-only histogram observation (no legacy key)."""
    _REGISTRY.observe(name, value)


# ---------------------------------------------------------------------------
# Link-capability cache (the one-shot startup probe, parallel/mesh.link_probe).
# Lives here — not in mesh — so the exchange timers can read the probed peaks
# without an import cycle, and survives registry resets within the process
# (the probe is one-shot per topology; a mid-run reset must not orphan it).
# ---------------------------------------------------------------------------

_LINK_CAPS: dict = {}


def set_link_caps(caps: dict) -> None:
    """Record the probed per-hop peaks ({"ici_gbps", "dcn_gbps", ...}) and
    mirror them into the registry for snapshots/Prometheus."""
    _LINK_CAPS.clear()
    _LINK_CAPS.update(caps)
    struct_set(None, "link_caps", dict(caps))


def link_caps() -> dict:
    """The probed per-hop peaks, or {} when no probe has run."""
    return dict(_LINK_CAPS)


def clear_link_caps() -> None:
    _LINK_CAPS.clear()
