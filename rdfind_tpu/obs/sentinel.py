"""Perf-regression sentinel: BENCH_HISTORY.jsonl + a noise-aware gate.

The bench trajectory used to live in loose one-off artifacts
(BENCH_r01…r05), so a regression only surfaced when a human re-read them
side by side.  The sentinel makes the trajectory a first-class series:

* :func:`append` flattens one bench.py result line into a history row —
  headline throughput plus the kernel/pipeline/exchange detail walls —
  keyed by provenance (git sha, n_cores, backend, resolved RDFIND_* knob
  set) and appends it to ``BENCH_HISTORY.jsonl``.
* :func:`check` compares the newest row against a trailing baseline of
  rows with the SAME (n_cores, backend, knobs) key — sha may differ; that
  is the axis under test — and flags a metric when it is worse than the
  baseline median by more than a threshold factor AND worse than the
  baseline's own observed spread explains.  Exit is nonzero on regression,
  so ``python -m rdfind_tpu.obs.sentinel --check`` gates CI
  (scripts/verify.sh wires it behind the tier-1 suite).

Noise awareness: with a single baseline row only the ratio test applies;
with more rows the worst historical ratio (max/median) widens the gate, so
a machine whose tiny-bench legitimately wobbles 1.4x does not page at the
default 1.5x threshold while a planted 2x slowdown still trips it.

Knobs: ``RDFIND_SENTINEL_THRESHOLD`` (worse-than-median factor, default
1.5) and ``RDFIND_SENTINEL_WINDOW`` (trailing baseline rows, default 5).

Stdlib-only (the obs contract); bench.py calls :func:`append` after every
run.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HISTORY_FILE = "BENCH_HISTORY.jsonl"
DEFAULT_THRESHOLD = 1.5
DEFAULT_WINDOW = 5

# (metric name, path into the bench result, direction).  "lower" = wall
# times (regression is bigger), "higher" = throughput (regression is
# smaller).  Paths that a run did not produce are simply absent from its
# row; check() only compares metrics present on both sides.
METRIC_SPECS = (
    ("headline_pairs_per_sec_per_chip", ("value",), "higher"),
    ("headline_wall_s", ("detail", "wall_s"), "lower"),
    ("s2l_wall_s", ("detail", "s2l", "wall_s"), "lower"),
    ("approx_wall_s", ("detail", "approx", "wall_s"), "lower"),
    ("pipelined_wall_s",
     ("detail", "pipelined_passes", "pipelined", "wall_s"), "lower"),
    ("sync_wall_s", ("detail", "pipelined_passes", "sync", "wall_s"),
     "lower"),
    ("exchange_flat_wall_s", ("detail", "exchange", "flat", "wall_s"),
     "lower"),
    ("exchange_hier_wall_s", ("detail", "exchange", "hier", "wall_s"),
     "lower"),
    # Ingest throughput rows (BENCH_INGEST_ONLY=1 runs promote detail.ingest
    # to the headline; full runs embed the same shape).  Keyed rows carry
    # n_cores in their provenance, so a 1-core proxy never baselines a
    # multicore box.
    ("ingest_serial_triples_per_sec",
     ("detail", "ingest", "serial", "triples_per_sec"), "higher"),
    ("ingest_parallel_triples_per_sec",
     ("detail", "ingest", "parallel", "triples_per_sec"), "higher"),
    ("ingest_parse_speedup_vs_legacy",
     ("detail", "ingest", "parse_speedup_vs_legacy"), "higher"),
    # Rung-3 kernel-mode walls (plane bits x emit_pipeline K-loop), from the
    # dict view of the per-mode rows (bench modes_by_name).  TPU-only in
    # practice: the CPU parity rows carry no pallas_ms, so extract() simply
    # skips them there.
    ("kernel_planes8_pallas_ms",
     ("detail", "pallas_vs_jnp", "modes_by_name", "planes8", "pallas_ms"),
     "lower"),
    ("kernel_planes8_emit_pallas_ms",
     ("detail", "pallas_vs_jnp", "modes_by_name", "planes8-emit",
      "pallas_ms"), "lower"),
    ("kernel_planes4_pallas_ms",
     ("detail", "pallas_vs_jnp", "modes_by_name", "planes4", "pallas_ms"),
     "lower"),
    ("kernel_planes4_emit_pallas_ms",
     ("detail", "pallas_vs_jnp", "modes_by_name", "planes4-emit",
      "pallas_ms"), "lower"),
    ("kernel_planes2_pallas_ms",
     ("detail", "pallas_vs_jnp", "modes_by_name", "planes2", "pallas_ms"),
     "lower"),
    ("kernel_planes2_emit_pallas_ms",
     ("detail", "pallas_vs_jnp", "modes_by_name", "planes2-emit",
      "pallas_ms"), "lower"),
    ("kernel_fused_wall_s",
     ("detail", "pallas_vs_jnp", "modes_by_name", "fused", "wall_s"),
     "lower"),
    ("kernel_materialized_wall_s",
     ("detail", "pallas_vs_jnp", "modes_by_name", "materialized", "wall_s"),
     "lower"),
    # Multi-chip kernel-feed rows (rung 3): per-chip throughput and the
    # stall fraction (exchange-wait ms / dense-compute ms — "can the
    # exchange plane keep the kernels fed"; lower is better, >= 1 means
    # exchange-bound).  overlap/scaling efficiencies regress downward.
    ("kernel_feed_mesh1_pairs_per_sec_per_chip",
     ("detail", "kernel_feed", "mesh1", "pairs_per_sec_per_chip"), "higher"),
    ("kernel_feed_mesh8_pairs_per_sec_per_chip",
     ("detail", "kernel_feed", "mesh8", "pairs_per_sec_per_chip"), "higher"),
    ("kernel_feed_mesh1_stall_fraction",
     ("detail", "kernel_feed", "mesh1", "kernel_feed_stall_fraction"),
     "lower"),
    ("kernel_feed_mesh8_stall_fraction",
     ("detail", "kernel_feed", "mesh8", "kernel_feed_stall_fraction"),
     "lower"),
    ("kernel_feed_mesh8_overlap_efficiency",
     ("detail", "kernel_feed", "mesh8", "overlap_efficiency"), "higher"),
    ("kernel_feed_scaling_efficiency",
     ("detail", "kernel_feed", "scaling_efficiency"), "higher"),
    # Sharded half-approximate two-round rows (bench_half_approx.py): mesh
    # {1,4,8} throughput, the mesh-8 per-device working set (pair buffers +
    # replicated sketch — the equal-memory bound), and the sketch
    # all-reduce's DCN bytes on the 2-host proxy, flat vs hierarchical (the
    # hier reduce must keep paying its factor-`local` cut).
    ("half_approx_mesh1_triples_per_sec",
     ("detail", "half_approx", "mesh1", "triples_per_sec"), "higher"),
    ("half_approx_mesh4_triples_per_sec",
     ("detail", "half_approx", "mesh4", "triples_per_sec"), "higher"),
    ("half_approx_mesh8_triples_per_sec",
     ("detail", "half_approx", "mesh8", "triples_per_sec"), "higher"),
    ("half_approx_mesh8_working_set_bytes",
     ("detail", "half_approx", "mesh8", "working_set_bytes_per_device"),
     "lower"),
    ("half_approx_sketch_dcn_bytes_flat",
     ("detail", "half_approx", "sketch_reduce", "dcn_bytes_flat"), "lower"),
    ("half_approx_sketch_dcn_bytes_hier",
     ("detail", "half_approx", "sketch_reduce", "dcn_bytes_hier"), "lower"),
    # Incremental-discovery rows (bench_delta.py): full-rerun wall over
    # --delta wall per change-batch size, plus the fraction of the pass
    # partition the 1% batch had to re-run (the "time proportional to the
    # change" claim made falsifiable — a regression here means the dirty
    # set stopped being sparse).
    ("delta_speedup_01pct",
     ("detail", "delta", "d01pct", "delta_speedup"), "higher"),
    ("delta_speedup_1pct",
     ("detail", "delta", "d1pct", "delta_speedup"), "higher"),
    ("delta_speedup_10pct",
     ("detail", "delta", "d10pct", "delta_speedup"), "higher"),
    ("delta_frac_passes_rerun_1pct",
     ("detail", "delta", "d1pct", "frac_passes_rerun"), "lower"),
    ("delta_wall_1pct_s",
     ("detail", "delta", "d1pct", "delta_wall_s"), "lower"),
    # Serving rows (bench_serve.py): the query plane's hot path gates like
    # a kernel — single-thread holds() QPS over the mmap'd index, the
    # O(header) open time (a regression here means something started
    # materializing sections at open), and the holds() tail latency.
    ("serve_qps", ("detail", "serve", "holds_qps"), "higher"),
    ("serve_open_ms", ("detail", "serve", "open_ms"), "lower"),
    ("serve_p99_us", ("detail", "serve", "holds_p99_us"), "lower"),
    # The observability plane's own contract (ISSUE 20): instrumented
    # service-path QPS, the telemetry overhead fraction, and the
    # bundle-commit -> serving-swap staleness across a live hot swap.
    ("serve_obs_qps",
     ("detail", "serve", "holds_qps_svc_obs"), "higher"),
    ("serve_obs_overhead_frac",
     ("detail", "serve", "obs_overhead_frac"), "lower"),
    ("serve_swap_staleness_s",
     ("detail", "serve", "swap_staleness_s"), "lower"),
)
_DIRECTIONS = {name: d for name, _, d in METRIC_SPECS}


def _git_sha() -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            capture_output=True, text=True, timeout=10)
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except Exception:
        return None


def resolved_knobs() -> dict[str, str]:
    """The RDFIND_* env as this process sees it — the knob half of a
    history row's identity (two rows with different knobs never compare)."""
    return {k: os.environ[k] for k in sorted(os.environ)
            if k.startswith("RDFIND_")}


def provenance(backend: str | None = None) -> dict:
    """The identity fields every bench row carries: git sha, core count,
    backend, and the resolved knob set."""
    return {"sha": _git_sha(), "n_cores": os.cpu_count(),
            "backend": backend, "knobs": resolved_knobs()}


def _dig(result: dict, path: tuple):
    cur = result
    for p in path:
        if not isinstance(cur, dict) or p not in cur:
            return None
        cur = cur[p]
    return cur


def extract_metrics(result: dict) -> dict[str, float]:
    out = {}
    for name, path, _direction in METRIC_SPECS:
        if path == ("value",) and result.get(
                "metric") != "cind_pairs_checked_per_sec_per_chip":
            # The top-level value is only the pairs/s headline on a full
            # bench.py row.  Promoted standalone rows (ingest-only,
            # kernel-modes, bench_delta) reuse the slot for a different
            # unit under the SAME provenance key — recording it as the
            # headline would fake a regression against the real headline
            # baseline.  Their numbers ride their own detail.* specs.
            continue
        v = _dig(result, path)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
            out[name] = float(v)
    return out


def build_row(result: dict, backend: str | None = None) -> dict:
    if backend is None:
        backend = _dig(result, ("detail", "backend"))
    row = {"ts": round(time.time(), 3), **provenance(backend=backend),
           "metrics": extract_metrics(result)}
    # Integrity plane (obs/integrity.py): the run's output digest plus the
    # workload it was computed over.  Digests only ever compare between rows
    # with the same provenance key AND the same workload — the tiny
    # verify.sh bench and a full bench must never cross-compare.
    dig = _dig(result, ("detail", "output_digest"))
    if dig:
        row["output_digest"] = str(dig)
        row["workload"] = _dig(result, ("detail", "workload"))
    return row


def default_history_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), HISTORY_FILE)


def append(result: dict, path: str | None = None,
           backend: str | None = None) -> dict:
    """Append one bench result as a history row; returns the row."""
    row = build_row(result, backend=backend)
    path = path or default_history_path()
    with open(path, "a") as f:
        f.write(json.dumps(row, default=str) + "\n")
    return row


def load_history(path: str | None = None) -> list[dict]:
    path = path or default_history_path()
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue  # torn tail line (a killed bench) is not fatal
    except OSError:
        pass
    return rows


def _row_key(row: dict) -> str:
    return json.dumps([row.get("n_cores"), row.get("backend"),
                       row.get("knobs", {})], sort_keys=True, default=str)


def check_verdict(path: str | None = None, threshold: float | None = None,
                  window: int | None = None) -> dict:
    """The structured verdict behind :func:`check` (and ``--json``).

    Returns {"ok", "status", "threshold", "window", "n_baseline", "sha",
    "metrics": {name: {value, median, worse_ratio, gate, regressed}},
    "regressions": [name...]}, where status is one of "no-history" /
    "no-baseline" / "ok" / "regression".
    """
    if threshold is None:
        threshold = float(os.environ.get("RDFIND_SENTINEL_THRESHOLD",
                                         str(DEFAULT_THRESHOLD)))
    if window is None:
        window = int(os.environ.get("RDFIND_SENTINEL_WINDOW",
                                    str(DEFAULT_WINDOW)))
    verdict = {"ok": True, "status": "no-history", "threshold": threshold,
               "window": window, "n_baseline": 0, "sha": None,
               "metrics": {}, "regressions": []}
    rows = load_history(path)
    if not rows:
        return verdict
    newest = rows[-1]
    verdict["sha"] = newest.get("sha")
    verdict["n_cores"] = newest.get("n_cores")
    verdict["backend"] = newest.get("backend")
    key = _row_key(newest)
    baseline = [r for r in rows[:-1] if _row_key(r) == key][-window:]
    if not baseline:
        verdict["status"] = "no-baseline"
        return verdict
    verdict["n_baseline"] = len(baseline)
    for name, value in sorted(newest.get("metrics", {}).items()):
        hist = [r["metrics"][name] for r in baseline
                if isinstance(r.get("metrics", {}).get(name), (int, float))]
        if not hist or value <= 0:
            continue
        hist.sort()
        median = hist[len(hist) // 2]
        if median <= 0:
            continue
        # worse-ratio > 1 means this row regressed vs the median.
        if _DIRECTIONS[name] == "lower":
            worse = value / median
            spread = max(hist) / median
        else:
            worse = median / value
            spread = median / min(hist)
        # Noise-aware gate: the baseline's own worst wobble (plus 10%
        # margin) widens the threshold — a jittery metric needs a bigger
        # excursion to page than a historically stable one.
        gate = max(threshold, spread * 1.1)
        regressed = worse > gate
        verdict["metrics"][name] = {
            "value": value, "median": median,
            "worse_ratio": round(worse, 3), "gate": round(gate, 3),
            "regressed": regressed}
        if regressed:
            verdict["regressions"].append(name)
    # Correctness gate (the integrity plane): an output-digest change at an
    # unchanged provenance key + workload is never noise — the run computed
    # a DIFFERENT result set on the same inputs.  No threshold, no spread:
    # any change regresses.
    new_dig = newest.get("output_digest")
    if new_dig:
        wl = json.dumps(newest.get("workload"), sort_keys=True, default=str)
        prior = sorted({r["output_digest"] for r in baseline
                        if r.get("output_digest")
                        and json.dumps(r.get("workload"), sort_keys=True,
                                       default=str) == wl})
        changed = bool(prior) and new_dig not in prior
        verdict["correctness"] = {"output_digest": new_dig,
                                  "baseline_digests": prior,
                                  "regressed": changed}
        if changed:
            verdict["regressions"].append("output_digest")
    verdict["ok"] = not verdict["regressions"]
    verdict["status"] = "ok" if verdict["ok"] else "regression"
    return verdict


def check(path: str | None = None, threshold: float | None = None,
          window: int | None = None) -> tuple[bool, list[str]]:
    """(ok, report_lines): newest row vs the trailing same-key baseline —
    the prose rendering of :func:`check_verdict` (exit semantics
    unchanged)."""
    v = check_verdict(path=path, threshold=threshold, window=window)
    if v["status"] == "no-history":
        return True, ["sentinel: no history rows — nothing to check"]
    if v["status"] == "no-baseline":
        return True, [f"sentinel: no baseline rows match "
                      f"(n_cores={v.get('n_cores')}, "
                      f"backend={v.get('backend')}) — pass by default"]
    lines = [f"sentinel: newest sha={v['sha']} vs "
             f"{v['n_baseline']} baseline row(s), threshold "
             f"{v['threshold']}x"]
    for name, m in sorted(v["metrics"].items()):
        verdict = "REGRESSION" if m["regressed"] else "ok"
        lines.append(f"  {name}: {m['value']} vs median {m['median']} "
                     f"(worse-ratio {m['worse_ratio']:.3f}, "
                     f"gate {m['gate']:.3f}) {verdict}")
    corr = v.get("correctness")
    if corr:
        verdict = ("CORRECTNESS REGRESSION" if corr["regressed"] else "ok")
        lines.append(f"  output_digest: {corr['output_digest']} vs baseline "
                     f"{corr['baseline_digests'] or ['(none)']} {verdict}")
    if v["regressions"]:
        lines.append(f"sentinel: REGRESSION in {', '.join(v['regressions'])}")
        return False, lines
    lines.append("sentinel: ok")
    return True, lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rdfind_tpu.obs.sentinel",
        description="Append bench.py result lines to BENCH_HISTORY.jsonl "
                    "and gate on noise-aware regression thresholds.")
    ap.add_argument("--check", action="store_true",
                    help="compare the newest history row against the "
                         "trailing baseline; exit 1 on regression")
    ap.add_argument("--append", metavar="FILE", default=None,
                    help="append the bench JSON line in FILE ('-' = stdin)")
    ap.add_argument("--history", default=None,
                    help=f"history path (default: repo {HISTORY_FILE})")
    ap.add_argument("--threshold", type=float, default=None,
                    help="worse-than-median factor that flags a regression "
                         f"(default {DEFAULT_THRESHOLD} or "
                         "RDFIND_SENTINEL_THRESHOLD)")
    ap.add_argument("--window", type=int, default=None,
                    help=f"trailing baseline rows (default {DEFAULT_WINDOW} "
                         "or RDFIND_SENTINEL_WINDOW)")
    ap.add_argument("--json", action="store_true",
                    help="--check: print ONE machine-readable JSON verdict "
                         "line (status, offending metrics, window size) "
                         "instead of the prose report; exit codes are "
                         "identical")
    args = ap.parse_args(argv)
    did = False
    if args.append is not None:
        text = (sys.stdin.read() if args.append == "-"
                else open(args.append).read())
        result = json.loads(text.strip().splitlines()[-1])
        row = append(result, path=args.history)
        print(f"sentinel: appended row sha={row['sha']} "
              f"metrics={sorted(row['metrics'])}")
        did = True
    if args.check:
        if args.json:
            v = check_verdict(path=args.history, threshold=args.threshold,
                              window=args.window)
            print(json.dumps(v, sort_keys=True, default=str))
            return 0 if v["ok"] else 1
        ok, lines = check(path=args.history, threshold=args.threshold,
                          window=args.window)
        print("\n".join(lines))
        return 0 if ok else 1
    if not did:
        ap.print_help()
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
