"""Run heartbeat/status file: is that long-running job wedged, or just slow?

The tracer updates ``heartbeat-host<N>.json`` in the run's obs directory on
every span boundary (throttled), recording the current stage, the dep-slice
pass index, and the last-event wall timestamp.  A watcher (tpu_watch.py
--status) reads it back: a recent timestamp means the run is alive however
slow; a stale one means it is wedged inside whatever stage/pass the file
names.  Writes are atomic (tmp + replace) so a reader never sees a torn
file.

Stdlib-only (the obs contract).
"""

from __future__ import annotations

import json
import os
import time

FILE_PREFIX = "heartbeat-host"

# Default staleness horizon: sharded passes on real workloads commit well
# under this; a heartbeat older than it means no span boundary fired at all.
DEFAULT_STALE_S = 300.0


def _path(directory: str, host_index: int) -> str:
    return os.path.join(directory, f"{FILE_PREFIX}{host_index}.json")


class Heartbeat:
    """Throttled status writer (at most one write per `min_interval_s`)."""

    def __init__(self, directory: str, host_index: int = 0,
                 min_interval_s: float = 1.0):
        self.dir = directory
        self.host_index = int(host_index)
        self.min_interval_s = float(min_interval_s)
        self._last = 0.0

    def maybe_beat(self, status: dict) -> None:
        now = time.monotonic()
        if now - self._last < self.min_interval_s:
            return
        self._last = now
        self.beat(status)

    def beat(self, status: dict, final: bool = False) -> None:
        payload = {**status, "host": self.host_index, "pid": os.getpid(),
                   "ts": time.time(), "final": bool(final)}
        tmp = _path(self.dir, self.host_index) + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, _path(self.dir, self.host_index))
        except OSError:
            pass  # liveness reporting must never fail the run


def write(directory: str, status: dict, host_index: int = 0) -> None:
    """One unthrottled heartbeat write (standalone writers, e.g. tpu_watch)."""
    os.makedirs(directory, exist_ok=True)
    Heartbeat(directory, host_index=host_index).beat(status)


def read(directory: str, host_index: int = 0) -> dict | None:
    """The host's last status, or None when absent/torn."""
    try:
        with open(_path(directory, host_index)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_all(directory: str) -> dict:
    """{host_index: status} for every heartbeat file in the directory."""
    out = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(FILE_PREFIX) and name.endswith(".json")):
            continue
        try:
            h = int(name[len(FILE_PREFIX):-len(".json")])
        except ValueError:
            continue
        status = read(directory, h)
        if status is not None:
            out[h] = status
    return out


def assess(directory: str, stale_s: float = DEFAULT_STALE_S,
           now: float | None = None) -> dict:
    """Liveness verdict over every host's heartbeat in the obs directory.

    Returns {"state": "missing"|"done"|"alive"|"wedged", "age_s", "hosts"}:
    `alive` = every heartbeat is fresh (the run may be slow, but spans are
    still closing); `wedged` = at least one NON-final host's last event is
    older than `stale_s` — a host that wrote its final beat is finished, not
    stuck, however old that beat is (an all-final set is "done", so a
    final-but-old host must never flip a still-working peer's run to
    "wedged"); `done` = every host wrote its final beat.  Hosts whose
    clocks run ahead of the assessor's produce negative ages, which are
    trivially fresh.  Hosts beating ``mode="serve"`` are exempt from the
    wedge check: a serving process is a long-lived idle loop with no pass
    progress by design, so an old-but-not-final serve beat means "idle",
    never "stuck" (its staleness is the watcher's SERVING-STALE concern,
    not a liveness verdict).
    """
    beats = read_all(directory)
    if not beats:
        return {"state": "missing", "age_s": None, "hosts": {}}
    now = time.time() if now is None else now
    ages = {h: round(now - b.get("ts", 0.0), 1) for h, b in beats.items()}
    if all(b.get("final") for b in beats.values()):
        state = "done"
    elif any(age > stale_s for h, age in ages.items()
             if not beats[h].get("final")
             and beats[h].get("mode") != "serve"):
        state = "wedged"
    else:
        state = "alive"
    return {"state": state, "age_s": max(ages.values()),
            "hosts": {h: {**beats[h], "age_s": ages[h]} for h in beats}}
