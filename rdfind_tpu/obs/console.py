"""The live run console: an off-by-default per-host stdlib HTTP server.

Until now the only ways to watch a running job were tail-ing heartbeat
files over a shared filesystem or waiting for the Prometheus exposition
file to flush.  The console serves the same state over HTTP while the run
is alive, one server per host (``RDFIND_CONSOLE_PORT`` or
``--console-port``; port 0 binds an ephemeral port, printed at startup):

  /metrics    the exact Prometheus text ``--metrics-file`` would write
  /status     liveness: this host's serving state + heartbeat.assess()
              over the obs directory when one is armed
  /progress   where the run is: current stage/pass, per-cap utilization
              (plan-time + per-pass trajectory), forecast advisories, and
              host skew.  The skew/cap structs are already allgathered by
              the sharded executor before they reach the registry, so the
              primary host's /progress IS the aggregated multi-host view.
  /datastats  the data plane: join-line histograms, capture spectra,
              block-skip effectiveness (obs/datastats.py's structs)
  /integrity  the integrity plane: per-stage content digests, verification
              counters, mismatch events (obs/integrity.py's structs)
  /flightrec  the crash-surviving ring (obs/flightrec.py), newest last

When a serving process arms an IndexService (set_query_service), the
console grows from a diagnostics endpoint into the query plane:

  /query/holds?dep=ID&ref=ID       does the CIND hold (capture ids, or
                                   dep_code/dep_v1/dep_v2 + ref_* string
                                   captures)
  /query/referenced?dep=ID[&limit] what the dependent references + support
  /query/topk?k=N                  the k CINDs with the largest support

and /status gains a "serving_index" struct (loaded vs on-disk generation,
pending-swap verdict, freshness, the loaded-generation certificate chain)
plus the named SLO verdict; the admin plane grows two more routes:

  /slo                             the SLO engine's verdict (ok/warn/
                                   burning + which SLO), its config, the
                                   freshness plane, and the aggregated
                                   request counters
  /debug/slowlog                   the bounded slow-query ring (args,
                                   latency, generation — obs/servestats)

and /metrics appends the sharded per-request serving stats (request
counters by endpoint×outcome, latency summaries) to the registry's
exposition.  Every non-200 the query plane returns is counted
(serve_http_400/serve_http_503 + the servestats outcome counters), so
refused or malformed traffic is visible, not silent.

Everything is read-only and served from in-process state (the registry,
the flight recorder, the heartbeat directory) — the handler threads never
touch device state, so a scrape cannot perturb the run.  The server binds
loopback by default; it is a debugging surface, not a product API.

Stdlib-only (the obs contract): http.server's ThreadingHTTPServer on a
daemon thread.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import flightrec, heartbeat, metrics, servestats

DEFAULT_HOST = "127.0.0.1"

# /progress picks these registry keys (when present) — the "where is the
# run and how much headroom is left" slice of the full snapshot.
_PROGRESS_KEYS = ("run_stage", "run_pass", "n_pair_passes", "planned_caps",
                  "cap_utilization", "cap_utilization_passes",
                  "cap_forecast", "cap_forecast_active", "host_skew",
                  "degradations", "ladder_rung")

_SERVER: ThreadingHTTPServer | None = None
_THREAD: threading.Thread | None = None
_OBS_DIR: str | None = None
_QUERY_SERVICE = None  # runtime.serving.IndexService when a server arms it


def env_port() -> int | None:
    """RDFIND_CONSOLE_PORT, or None when unset/blank/non-numeric."""
    v = os.environ.get("RDFIND_CONSOLE_PORT", "").strip()
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        return None


def serving() -> bool:
    return _SERVER is not None


def port() -> int | None:
    """The bound port (resolves port-0 ephemeral binds), or None."""
    return _SERVER.server_address[1] if _SERVER is not None else None


def set_obs_dir(directory: str | None) -> None:
    """Point /status at the run's heartbeat directory (driver wires this
    when tracing and the console are both armed)."""
    global _OBS_DIR
    _OBS_DIR = directory


def set_query_service(service) -> None:
    """Arm the /query/* routes with a runtime.serving.IndexService (the
    serving process wires this; None disarms)."""
    global _QUERY_SERVICE
    _QUERY_SERVICE = service


def start(bind_port: int = 0, host: str = DEFAULT_HOST,
          obs_dir: str | None = None) -> int | None:
    """Start the console (idempotent); returns the bound port, or None when
    the bind fails — a console that cannot bind must never fail the run."""
    global _SERVER, _THREAD
    if _SERVER is not None:
        return _SERVER.server_address[1]
    try:
        server = ThreadingHTTPServer((host, int(bind_port)), _Handler)
    except OSError:
        return None
    server.daemon_threads = True
    if obs_dir is not None:
        set_obs_dir(obs_dir)
    _SERVER = server
    _THREAD = threading.Thread(target=server.serve_forever,
                               name="rdfind-console", daemon=True)
    _THREAD.start()
    return server.server_address[1]


def stop() -> None:
    global _SERVER, _THREAD
    server, _SERVER = _SERVER, None
    if server is None:
        return
    try:
        server.shutdown()
        server.server_close()
    except Exception:
        pass
    if _THREAD is not None:
        _THREAD.join(timeout=5.0)
        _THREAD = None
    set_obs_dir(None)
    set_query_service(None)


# ---------------------------------------------------------------------------
# Endpoint payload builders (module functions so tests can call them without
# a socket).
# ---------------------------------------------------------------------------


def progress_payload() -> dict:
    snap = metrics.registry().snapshot(jsonable=True)
    out = {k: snap[k] for k in _PROGRESS_KEYS if k in snap}
    out.setdefault("run_stage", None)
    out.setdefault("run_pass", None)
    return out


def datastats_payload() -> dict:
    snap = metrics.registry().snapshot(jsonable=True)
    return {k: v for k, v in sorted(snap.items())
            if k.startswith("datastats_")}


def integrity_payload() -> dict:
    """The integrity plane's live view: stage digests, verification
    counters, and any mismatch events (obs/integrity.py's structs)."""
    snap = metrics.registry().snapshot(jsonable=True)
    return {k: v for k, v in sorted(snap.items())
            if k.startswith("integrity")}


def status_payload() -> dict:
    out = {"serving": True, "pid": os.getpid(), "obs_dir": _OBS_DIR}
    if _OBS_DIR:
        out["heartbeat"] = heartbeat.assess(_OBS_DIR)
    if _QUERY_SERVICE is not None:
        out["serving_index"] = _QUERY_SERVICE.status()
        out["slo"] = servestats.evaluate_slo(
            out["serving_index"].get("freshness"))
    return out


def slo_payload() -> dict:
    """The /slo admin view: the named verdict, the engine's targets, the
    freshness plane, and the aggregated per-request counters."""
    fresh = (_QUERY_SERVICE.freshness()
             if _QUERY_SERVICE is not None else None)
    return {"verdict": servestats.evaluate_slo(fresh),
            "config": servestats.slo_config(),
            "freshness": fresh,
            "requests": servestats.aggregate()}


def slowlog_payload() -> dict:
    entries = servestats.slowlog()
    return {"enabled": servestats.enabled(),
            "slow_us": servestats.slow_us(),
            "n_entries": len(entries), "entries": entries}


def _reject(endpoint: str, payload: dict, code: int) -> tuple[dict, int]:
    """Route a non-200 query answer through the counters (ISSUE 20
    satellite bugfix: refused/malformed traffic used to vanish — no
    counter anywhere)."""
    servestats.record(endpoint, str(code))
    metrics.counter_add(None, f"serve_http_{code}")
    return payload, code


def _capture_arg(q: dict, role: str):
    """A capture from query params: `role`=ID (capture id) or the string
    triple `role`_code/`role`_v1/`role`_v2.  Raises ValueError when absent
    or malformed."""
    if role in q:
        return int(q[role][0])
    code_key = f"{role}_code"
    if code_key not in q:
        raise ValueError(f"missing {role} (give {role}=<capture id> or "
                         f"{role}_code/{role}_v1/{role}_v2)")
    v1 = q.get(f"{role}_v1", [None])[0]
    v2 = q.get(f"{role}_v2", [None])[0]
    return (int(q[code_key][0]), v1, v2)


def _answer(endpoint: str, payload: dict) -> tuple[dict, int]:
    """An IndexService answer → (payload, HTTP code).  'no index loaded'
    is a 503, not a 200: the service already counted the refusal; the
    HTTP plane only maps the code (and counts it)."""
    if payload.get("error") == "no index loaded":
        metrics.counter_add(None, "serve_http_503")
        return payload, 503
    return payload, 200


def query_holds_payload(query: str) -> tuple[dict, int]:
    if _QUERY_SERVICE is None:
        return _reject("holds", {"error": "no query service armed"}, 503)
    q = urllib.parse.parse_qs(query)
    try:
        dep = _capture_arg(q, "dep")
        ref = _capture_arg(q, "ref")
    except ValueError as e:
        return _reject("holds", {"error": str(e)}, 400)
    return _answer("holds", _QUERY_SERVICE.query_holds(dep, ref))


def query_referenced_payload(query: str) -> tuple[dict, int]:
    if _QUERY_SERVICE is None:
        return _reject("referenced",
                       {"error": "no query service armed"}, 503)
    q = urllib.parse.parse_qs(query)
    try:
        dep = _capture_arg(q, "dep")
        limit = int(q["limit"][0]) if "limit" in q else None
    except ValueError as e:
        return _reject("referenced", {"error": str(e)}, 400)
    return _answer("referenced",
                   _QUERY_SERVICE.query_referenced(dep, limit=limit))


def query_topk_payload(query: str) -> tuple[dict, int]:
    if _QUERY_SERVICE is None:
        return _reject("topk", {"error": "no query service armed"}, 503)
    q = urllib.parse.parse_qs(query)
    try:
        k = int(q.get("k", ["10"])[0])
    except ValueError as e:
        return _reject("topk", {"error": str(e)}, 400)
    return _answer("topk", _QUERY_SERVICE.query_topk(k))


class _Handler(BaseHTTPRequestHandler):
    # A scrape must never spam the run's stderr.
    def log_message(self, fmt, *args):  # noqa: D102 (http.server API)
        pass

    def _send(self, body: str, content_type: str, code: int = 200) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass  # the scraper hung up; the run does not care

    def _send_json(self, payload, code: int = 200) -> None:
        self._send(json.dumps(payload, indent=1, default=str) + "\n",
                   "application/json", code)

    def do_GET(self):  # noqa: N802 (http.server API)
        parts = self.path.split("?", 1)
        path = parts[0].rstrip("/") or "/"
        query = parts[1] if len(parts) > 1 else ""
        try:
            if path == "/metrics":
                body = metrics.registry().prometheus_text()
                if _QUERY_SERVICE is not None:
                    body += servestats.prometheus_text()
                self._send(body, "text/plain; version=0.0.4")
            elif path == "/status":
                self._send_json(status_payload())
            elif path == "/progress":
                self._send_json(progress_payload())
            elif path == "/datastats":
                self._send_json(datastats_payload())
            elif path == "/integrity":
                self._send_json(integrity_payload())
            elif path == "/flightrec":
                self._send_json({"enabled": flightrec.enabled(),
                                 "events": flightrec.snapshot()})
            elif path == "/slo":
                self._send_json(slo_payload())
            elif path == "/debug/slowlog":
                self._send_json(slowlog_payload())
            elif path == "/query/holds":
                self._send_json(*query_holds_payload(query))
            elif path == "/query/referenced":
                self._send_json(*query_referenced_payload(query))
            elif path == "/query/topk":
                self._send_json(*query_topk_payload(query))
            elif path == "/":
                endpoints = ["/metrics", "/status", "/progress",
                             "/datastats", "/integrity", "/flightrec",
                             "/slo", "/debug/slowlog"]
                if _QUERY_SERVICE is not None:
                    endpoints += ["/query/holds", "/query/referenced",
                                  "/query/topk"]
                self._send_json({"endpoints": endpoints})
            else:
                self._send_json({"error": f"unknown path {path}"}, code=404)
        except Exception as e:  # a bad scrape must never kill the thread
            self._send_json({"error": repr(e)}, code=500)
