"""RDF prefix handling + URL shortening + asciification.

Replaces ParseRdfPrefixes (operators/ParseRdfPrefixes.scala:12-28), ShortenUrls
(operators/ShortenUrls.scala:16-59, longest-prefix match via a squashed StringTrie)
and AsciifyTriples (operators/AsciifyTriples.scala:10-46).
"""

from __future__ import annotations

import unicodedata

from ..utils.trie import StringTrie


def parse_prefix_line(line: str) -> tuple[str, str] | None:
    """'@prefix ex: <http://example.org/> .' -> ('ex:', 'http://example.org/')."""
    line = line.strip()
    if not line.lower().startswith("@prefix"):
        return None
    rest = line[len("@prefix"):].strip()
    try:
        name, url_part = rest.split(None, 1)
    except ValueError:
        return None
    url_part = url_part.strip()
    if url_part.endswith("."):
        url_part = url_part[:-1].strip()
    if url_part.startswith("<") and url_part.endswith(">"):
        url_part = url_part[1:-1]
    return name, url_part


def build_prefix_trie(prefix_pairs) -> StringTrie:
    """Trie mapping URL -> short prefix name, squashed for fast longest-prefix hits."""
    trie = StringTrie()
    for name, url in prefix_pairs:
        trie[url] = name
    trie.squash()
    return trie


def shorten_term(term: str, trie: StringTrie, prefix_urls: dict[str, str]) -> str:
    """Replace the longest matching URL prefix inside an <IRI> term with its name."""
    if not (term.startswith("<") and term.endswith(">")):
        return term
    url = term[1:-1]
    name = trie.longest_prefix_value(url)
    if name is None:
        return term
    return name + url[len(prefix_urls[name]):]


def asciify(value: str) -> str:
    """Fold non-ASCII characters to 7-bit (AsciifyTriples semantics: best-effort
    transliteration, unmappable characters replaced)."""
    if value.isascii():
        return value
    decomposed = unicodedata.normalize("NFKD", value)
    stripped = "".join(ch for ch in decomposed if not unicodedata.combining(ch))
    return stripped.encode("ascii", "replace").decode("ascii")
