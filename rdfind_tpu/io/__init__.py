"""Host ingest: multi-file gz-aware reading, N-Triples/N-Quads parsing, prefix
shortening.  The analog of rdfind-flink's persistence layer
(MultiFileTextInputFormat.java:49-368) plus the rdf-converter parsers."""
