"""ctypes bindings for the native ingest runtime (native/rdfind_native.cpp).

The native path fuses read + gz decompression + tokenization + interning into
one C++ pass and hands back the (N, 3) int32 id table directly — the hot ingest
path for large dumps.  The pure-Python path (io/reader.py + io/ntriples.py +
dictionary.intern_triples) remains the reference implementation and the
fallback when the shared library is absent and cannot be built.

Semantics: identical ids/values for valid-UTF-8 inputs (byte-sort order ==
np.unique's code-point order).  For invalid UTF-8 the native path is strictly
more exact: it interns raw bytes (distinct byte strings stay distinct), while
the Python reader's errors="replace" can conflate them; exported values are
decoded with errors="replace" either way.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from ..dictionary import Dictionary

_SO_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "_rdfind_native.so")
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")

_lib = None
_lib_error: str | None = None


class NativeIngestError(RuntimeError):
    pass


def _build() -> bool:
    """Best-effort build of the shared library via the checked-in Makefile."""
    if not os.path.isdir(_NATIVE_DIR):
        return False
    try:
        proc = subprocess.run(["make", "-C", _NATIVE_DIR],
                              capture_output=True, text=True, timeout=120)
        return proc.returncode == 0 and os.path.exists(_SO_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False


def _bind(lib):
    lib.rdf_ingest_new.restype = ctypes.c_void_p
    lib.rdf_ingest_free.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_error.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_error.restype = ctypes.c_char_p
    lib.rdf_ingest_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.rdf_ingest_file.restype = ctypes.c_int64
    lib.rdf_ingest_finalize.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_finalize.restype = ctypes.c_int64
    lib.rdf_ingest_num_triples.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_num_triples.restype = ctypes.c_int64
    lib.rdf_ingest_get_triples.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.rdf_ingest_values_bytes.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_values_bytes.restype = ctypes.c_int64
    lib.rdf_ingest_get_values.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_void_p]
    return lib


def load():
    """The bound library, building it on first use; None if unavailable."""
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    if os.environ.get("RDFIND_NATIVE", "").lower() in ("0", "false", "no"):
        _lib_error = "disabled via RDFIND_NATIVE"
        return None
    if not os.path.exists(_SO_PATH) and not _build():
        _lib_error = "shared library missing and build failed"
        return None
    try:
        _lib = _bind(ctypes.CDLL(_SO_PATH))
    except OSError as e:
        _lib_error = str(e)
        return None
    return _lib


def available() -> bool:
    return load() is not None


def ingest_files(paths, tabs: bool = False, expect_quad: bool = False,
                 skip_comments: bool = True):
    """Parse + intern all files natively.  Returns ((N, 3) int32 ids, Dictionary).

    Raises NativeIngestError on parse errors (same failure surface as the
    Python parser's ParseError) or if the library is unavailable.
    """
    lib = load()
    if lib is None:
        raise NativeIngestError(f"native ingest unavailable: {_lib_error}")
    h = lib.rdf_ingest_new()
    try:
        for p in paths:
            rc = lib.rdf_ingest_file(h, os.fspath(p).encode(), int(tabs),
                                     int(expect_quad), int(skip_comments))
            if rc < 0:
                raise NativeIngestError(
                    lib.rdf_ingest_error(h).decode(errors="replace"))
        n_values = lib.rdf_ingest_finalize(h)
        n_triples = lib.rdf_ingest_num_triples(h)
        ids = np.empty((n_triples, 3), np.int32)
        if n_triples:
            lib.rdf_ingest_get_triples(h, ids.ctypes.data_as(ctypes.c_void_p))
        nbytes = lib.rdf_ingest_values_bytes(h)
        buf = np.empty(nbytes, np.uint8)
        offsets = np.empty(n_values + 1, np.int64)
        lib.rdf_ingest_get_values(
            h, buf.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p))
    finally:
        lib.rdf_ingest_free(h)
    raw = buf.tobytes()
    values = np.empty(n_values, object)
    # Probe losslessness per value, not on the concatenated blob: an invalid
    # suffix of one value can splice with an invalid prefix of the next into a
    # valid sequence (b"\xc3" + b"\xa9" == "é"), so a whole-blob decode can
    # succeed while individual values are invalid.
    lossless = True
    for i in range(n_values):
        chunk = raw[offsets[i]:offsets[i + 1]]
        try:
            values[i] = chunk.decode("utf-8")
        except UnicodeDecodeError:
            values[i] = chunk.decode(errors="replace")
            lossless = False
    if not lossless and n_values:
        # Invalid UTF-8: errors="replace" can reorder or even conflate values
        # relative to the native byte-sort ranks, breaking Dictionary's
        # sorted-unique invariant.  Re-canonicalize exactly like the Python
        # path (np.unique on decoded strings) and remap the ids.
        uniques, inverse = np.unique(values, return_inverse=True)
        ids = inverse.astype(np.int32)[ids]
        values = uniques
    return ids, Dictionary(values)
