"""ctypes bindings for the native ingest runtime (native/rdfind_native.cpp).

The native path fuses read + gz decompression + tokenization + interning into
one C++ pass and hands back the (N, 3) int32 id table directly — the hot ingest
path for large dumps.  The pure-Python path (io/reader.py + io/ntriples.py +
dictionary.intern_triples) remains the reference implementation and the
fallback when the shared library is absent and cannot be built.

Parallelism: ``RDFIND_INGEST_THREADS`` (default: physical cores, clamped to
the process affinity mask — hyperthread oversubscription measured 0.62x;
``1`` restores the single-threaded serial engine) runs the parse as a
work-stealing unit queue — byte-range chunks of plain files split at newline
boundaries (``RDFIND_INGEST_CHUNK_BYTES``; unset auto-sizes the grain to
``input_bytes / (threads * 4)``), exact gzip members of multi-member .gz
files, and decode→parse pipelined subtasks of large single-member .gz files.
Committed triple blocks stream back IN INPUT ORDER while later units still
parse (:class:`IngestStream`), so the caller's host-side assembly — and any
staging it feeds, e.g. runtime/multihost_ingest.py's per-host table build —
overlaps the parse instead of following it.  Ids are bit-identical to the
serial path by construction: the merge stage hash-partitions the per-thread
interners with the SAME crc32 partition function as the multi-host
dictionary (dictionary.value_shard), dedupes shards in parallel, and
byte-sort-merges them into the global rank order.

Speed rungs (each its own env knob, resolved here and pushed into the C
engine via ``rdf_ingest_set_opts`` so a stale .so fails the bind cleanly):
``RDFIND_INGEST_SWAR`` (8-byte SWAR delimiter scanning; 0 = scalar oracle),
``RDFIND_INGEST_MMAP`` (mmap plain files + zero-copy interning; 0 = fread +
arena copies), ``RDFIND_INGEST_GZ_PIPELINE`` (parallel gzip; 0 = one unit
per .gz), ``RDFIND_INGEST_GZ_CHUNK_BYTES`` (decoded bytes per pipelined gz
subtask, default 8 MiB — also the compressed-size floor below which a gz
stays unpipelined).

Semantics: identical ids/values to the Python path for valid-UTF-8 inputs
(byte-sort order == np.unique's code-point order).  For invalid UTF-8 the
native path is strictly more exact: it interns raw bytes (distinct byte
strings stay distinct), while the Python reader's errors="replace" can
conflate them; exported values are decoded with errors="replace" either way.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import time

import numpy as np

from ..dictionary import Dictionary
from ..obs import metrics, tracer

_SO_PATH = os.environ.get("RDFIND_NATIVE_SO") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "_rdfind_native.so")
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")

_lib = None
_lib_error: str | None = None

# rdf_ingest_stats2 lane order (native/rdfind_native.cpp); the first 12
# match the legacy rdf_ingest_stats layout.
_STAT_FIELDS = ("bytes_read", "read_ms", "parse_ms", "intern_ms", "merge_ms",
                "remap_ms", "n_threads", "n_units", "queue_stalls",
                "queue_stall_ms", "n_files", "decode_ms", "mmap_bytes",
                "n_gz_members", "n_gz_subtasks", "swar", "mmap",
                "gz_pipeline")
_N_STATS = len(_STAT_FIELDS)
_INT_STATS = ("bytes_read", "n_threads", "n_units", "queue_stalls", "n_files",
              "mmap_bytes", "n_gz_members", "n_gz_subtasks", "swar", "mmap",
              "gz_pipeline")

DEFAULT_GZ_CHUNK_BYTES = 8 << 20


class NativeIngestError(RuntimeError):
    pass


def physical_cores() -> int:
    """Physical core count (SMT siblings collapsed), via sysfs topology.

    Hyperthread oversubscription is where the 0.62x parallel-vs-serial row
    came from: two parse workers sharing one core's load/store ports lose
    more to interner cache thrash than they gain.  Falls back to
    os.cpu_count() when the topology files are absent (containers, macOS).
    """
    try:
        seen = set()
        base = "/sys/devices/system/cpu"
        for name in os.listdir(base):
            if not (name.startswith("cpu") and name[3:].isdigit()):
                continue
            sib = os.path.join(base, name, "topology", "thread_siblings_list")
            with open(sib) as f:
                seen.add(f.read().strip())
        if seen:
            return len(seen)
    except OSError:
        pass
    return os.cpu_count() or 1


def ingest_threads(threads: int | None = None) -> int:
    """Resolved worker count: explicit arg > RDFIND_INGEST_THREADS > auto.

    Auto clamps to physical cores AND the process affinity mask (cgroup /
    taskset limits) — whichever is smaller.
    """
    if threads is None:
        env = os.environ.get("RDFIND_INGEST_THREADS", "")
        if env.strip():
            threads = int(env)
        else:
            threads = physical_cores()
            try:
                threads = min(threads, len(os.sched_getaffinity(0)))
            except (AttributeError, OSError):
                pass
    return max(1, int(threads))


def ingest_chunk_bytes(chunk_bytes: int | None = None) -> int:
    """Resolved plain-file split size; 0 = auto (native engine sizes the
    grain to input_bytes / (threads * 4), clamped to [1 MiB, 64 MiB])."""
    if chunk_bytes is None:
        env = os.environ.get("RDFIND_INGEST_CHUNK_BYTES", "")
        chunk_bytes = int(env) if env.strip() else 0
    return max(0, int(chunk_bytes))


def _env_flag(name: str, default: bool = True) -> bool:
    v = os.environ.get(name, "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "no")


def ingest_swar() -> bool:
    """RDFIND_INGEST_SWAR: 8-byte SWAR delimiter scanning (0 = scalar)."""
    return _env_flag("RDFIND_INGEST_SWAR")


def ingest_mmap() -> bool:
    """RDFIND_INGEST_MMAP: mmap plain files + zero-copy interning."""
    return _env_flag("RDFIND_INGEST_MMAP")


def ingest_gz_pipeline() -> bool:
    """RDFIND_INGEST_GZ_PIPELINE: member fan-out + decode→parse pipeline."""
    return _env_flag("RDFIND_INGEST_GZ_PIPELINE")


def ingest_gz_chunk_bytes() -> int:
    """RDFIND_INGEST_GZ_CHUNK_BYTES: decoded bytes per gz pipeline subtask."""
    env = os.environ.get("RDFIND_INGEST_GZ_CHUNK_BYTES", "")
    return max(256, int(env)) if env.strip() else DEFAULT_GZ_CHUNK_BYTES


def _build() -> bool:
    """Best-effort build of the shared library via the checked-in Makefile."""
    if not os.path.isdir(_NATIVE_DIR):
        return False
    try:
        proc = subprocess.run(["make", "-C", _NATIVE_DIR],
                              capture_output=True, text=True, timeout=120)
        return proc.returncode == 0 and os.path.exists(_SO_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False


def _bind(lib):
    lib.rdf_ingest_new.restype = ctypes.c_void_p
    lib.rdf_ingest_free.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_error.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_error.restype = ctypes.c_char_p
    lib.rdf_ingest_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.rdf_ingest_file.restype = ctypes.c_int64
    lib.rdf_ingest_finalize.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_finalize.restype = ctypes.c_int64
    lib.rdf_ingest_num_triples.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_num_triples.restype = ctypes.c_int64
    lib.rdf_ingest_get_triples.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.rdf_ingest_values_bytes.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_values_bytes.restype = ctypes.c_int64
    lib.rdf_ingest_get_values.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_void_p]
    lib.rdf_ingest_begin.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int64,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int64]
    lib.rdf_ingest_begin.restype = ctypes.c_int64
    lib.rdf_ingest_next_block.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_next_block.restype = ctypes.c_int64
    lib.rdf_ingest_block_thread.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_block_thread.restype = ctypes.c_int
    lib.rdf_ingest_block_copy.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.rdf_ingest_stream_finish.argtypes = [ctypes.c_void_p]
    lib.rdf_ingest_stream_finish.restype = ctypes.c_int64
    lib.rdf_ingest_thread_vocab.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rdf_ingest_thread_vocab.restype = ctypes.c_int64
    lib.rdf_ingest_thread_remap.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                            ctypes.c_void_p]
    lib.rdf_ingest_stats.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    # PR-10 API: options push + 18-lane stats.  Binding these here means a
    # stale .so raises AttributeError in load() -> clean Python fallback.
    lib.rdf_ingest_set_opts.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_int, ctypes.c_int64,
                                        ctypes.c_int]
    lib.rdf_ingest_stats2.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_int64]
    lib.rdf_ingest_stats2.restype = ctypes.c_int64
    return lib


def _apply_opts(lib, h) -> None:
    """Push the env-resolved speed-rung knobs into a fresh ingest handle."""
    lib.rdf_ingest_set_opts(h, int(ingest_swar()), int(ingest_mmap()),
                            ingest_gz_chunk_bytes(),
                            int(ingest_gz_pipeline()))


def load():
    """The bound library, building it on first use; None if unavailable."""
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    if os.environ.get("RDFIND_NATIVE", "").lower() in ("0", "false", "no"):
        _lib_error = "disabled via RDFIND_NATIVE"
        return None
    if not os.path.exists(_SO_PATH) and not _build():
        _lib_error = "shared library missing and build failed"
        return None
    try:
        _lib = _bind(ctypes.CDLL(_SO_PATH))
    except (OSError, AttributeError) as e:
        # AttributeError == a stale .so predating the streaming API.
        _lib = None
        _lib_error = str(e)
        return None
    return _lib


def available() -> bool:
    return load() is not None


def _read_stats(lib, h) -> dict:
    buf = (ctypes.c_double * _N_STATS)()
    lib.rdf_ingest_stats2(h, buf, _N_STATS)
    out = {k: float(v) for k, v in zip(_STAT_FIELDS, buf)}
    for k in _INT_STATS:
        out[k] = int(out[k])
    return out


def _values_from_buffer(raw: bytes, offsets: np.ndarray):
    """Per-value UTF-8 decode of the exported dictionary blob.

    Probes losslessness per value, not on the concatenated blob: an invalid
    suffix of one value can splice with an invalid prefix of the next into a
    valid sequence (b"\\xc3" + b"\\xa9" == "é"), so a whole-blob decode can
    succeed while individual values are invalid.  Returns (values, lossless).
    """
    n_values = len(offsets) - 1
    values = np.empty(n_values, object)
    lossless = True
    for i in range(n_values):
        chunk = raw[offsets[i]:offsets[i + 1]]
        try:
            values[i] = chunk.decode("utf-8")
        except UnicodeDecodeError:
            values[i] = chunk.decode(errors="replace")
            lossless = False
    return values, lossless


def canonicalize(ids: np.ndarray, values: np.ndarray, lossless: bool):
    """Invalid UTF-8: errors="replace" can reorder or even conflate values
    relative to the native byte-sort ranks, breaking Dictionary's
    sorted-unique invariant.  Re-canonicalize exactly like the Python path
    (np.unique on decoded strings) and remap the ids."""
    if lossless or not len(values):
        return ids, Dictionary(values)
    uniques, inverse = np.unique(values, return_inverse=True)
    ids = inverse.astype(np.int32)[ids]
    return ids, Dictionary(uniques)


class IngestStream:
    """Streaming parallel ingest: committed triple blocks while files parse.

    Usage::

        stream = IngestStream(paths, tabs=..., expect_quad=...)
        for block, thread_id in stream:   # provisional thread-local ids
            ...stage block...             # overlaps the ongoing parse
        remaps = stream.finish()          # thread-local id -> global rank
        values = stream.values()          # byte-sorted distinct values
        st = stream.stats()
        stream.close()

    Blocks arrive in INPUT ORDER (file order; a split file's chunks in offset
    order), so concatenating them reproduces the serial triple order exactly;
    applying ``remaps[thread_id]`` to each block yields the final global ids,
    bit-identical to the serial engine.
    """

    def __init__(self, paths, *, tabs: bool = False, expect_quad: bool = False,
                 skip_comments: bool = True, threads: int | None = None,
                 chunk_bytes: int | None = None):
        lib = load()
        if lib is None:
            raise NativeIngestError(f"native ingest unavailable: {_lib_error}")
        self._lib = lib
        self._h = lib.rdf_ingest_new()
        _apply_opts(lib, self._h)
        self.n_threads = ingest_threads(threads)
        encoded = [os.fspath(p).encode() for p in paths]
        arr = (ctypes.c_char_p * max(len(encoded), 1))(*encoded)
        n_units = lib.rdf_ingest_begin(
            self._h, arr, len(encoded), int(tabs), int(expect_quad),
            int(skip_comments), self.n_threads,
            ingest_chunk_bytes(chunk_bytes))
        if n_units < 0:
            msg = lib.rdf_ingest_error(self._h).decode(errors="replace")
            self.close()
            raise NativeIngestError(msg)
        self._finished = False

    def __iter__(self):
        lib, h = self._lib, self._h
        while True:
            n = lib.rdf_ingest_next_block(h)
            if n == -1:
                return
            if n < 0:
                raise NativeIngestError(
                    lib.rdf_ingest_error(h).decode(errors="replace"))
            block = np.empty((int(n), 3), np.int32)
            if n:
                lib.rdf_ingest_block_copy(
                    h, block.ctypes.data_as(ctypes.c_void_p))
            yield block, int(lib.rdf_ingest_block_thread(h))

    def finish(self) -> list[np.ndarray]:
        """Merge the per-thread interners; returns per-thread local->global
        remap tables.  Only valid after the block iterator is exhausted."""
        n_values = self._lib.rdf_ingest_stream_finish(self._h)
        if n_values < 0:
            raise NativeIngestError(
                self._lib.rdf_ingest_error(self._h).decode(errors="replace"))
        self._finished = True
        remaps = []
        for t in range(self.n_threads):
            vocab = int(self._lib.rdf_ingest_thread_vocab(self._h, t))
            r = np.empty(max(vocab, 1), np.int32)
            if vocab:
                self._lib.rdf_ingest_thread_remap(
                    self._h, t, r.ctypes.data_as(ctypes.c_void_p))
            remaps.append(r[:vocab])
        self._n_values = int(n_values)
        return remaps

    def raw_values(self) -> tuple[bytes, np.ndarray]:
        """(concatenated byte blob, offsets) of the sorted distinct values."""
        nbytes = int(self._lib.rdf_ingest_values_bytes(self._h))
        buf = np.empty(max(nbytes, 1), np.uint8)
        offsets = np.empty(self._n_values + 1, np.int64)
        self._lib.rdf_ingest_get_values(
            self._h, buf.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p))
        return buf.tobytes()[:nbytes], offsets

    def decoded_values(self):
        """(values, lossless): the sorted distinct values, UTF-8-decoded
        per value; pair with :func:`canonicalize` to build the Dictionary."""
        raw, offsets = self.raw_values()
        return _values_from_buffer(raw, offsets)

    def stats(self) -> dict:
        return _read_stats(self._lib, self._h)

    def close(self):
        if self._h is not None:
            self._lib.rdf_ingest_free(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BlockAssembler:
    """Incremental (N, 3) table assembly from streamed blocks.

    Grows the backing array by doubling so each committed block costs one
    memcpy DURING the parse (instead of a full second concatenation pass
    after it), and records per-block spans so the thread-local -> global id
    remap applies vectorized per span at finish."""

    def __init__(self):
        self._buf = np.empty((1 << 14, 3), np.int32)
        self._n = 0
        self._spans: list[tuple[int, int, int]] = []  # (lo, hi, thread)

    def add(self, block: np.ndarray, thread_id: int):
        n = block.shape[0]
        if n == 0:
            return
        while self._n + n > self._buf.shape[0]:
            grown = np.empty((self._buf.shape[0] * 2, 3), np.int32)
            grown[:self._n] = self._buf[:self._n]
            self._buf = grown
        self._buf[self._n:self._n + n] = block
        self._spans.append((self._n, self._n + n, thread_id))
        self._n += n

    def finalize(self, remaps: list[np.ndarray]) -> np.ndarray:
        """Applies the per-thread remap tables in place; returns the table."""
        ids = self._buf[:self._n]
        for lo, hi, t in self._spans:
            ids[lo:hi] = remaps[t][ids[lo:hi]]
        return ids


def _ingest_parallel(paths, tabs, expect_quad, skip_comments, threads,
                     chunk_bytes, stats):
    t_wall = time.perf_counter()
    with tracer.span("ingest-parallel", cat=tracer.CAT_STAGE,
                     files=len(paths), threads=ingest_threads(threads)):
        with IngestStream(paths, tabs=tabs, expect_quad=expect_quad,
                          skip_comments=skip_comments, threads=threads,
                          chunk_bytes=chunk_bytes) as stream:
            asm = BlockAssembler()
            with tracer.span("ingest-stream", cat=tracer.CAT_STAGE):
                for block, thread_id in stream:
                    asm.add(block, thread_id)
            with tracer.span("ingest-merge", cat=tracer.CAT_STAGE):
                remaps = stream.finish()
            with tracer.span("ingest-remap", cat=tracer.CAT_STAGE):
                t0 = time.perf_counter()
                ids = asm.finalize(remaps)
                remap_ms = (time.perf_counter() - t0) * 1000.0
            raw, offsets = stream.raw_values()
            st = stream.stats()
        values, lossless = _values_from_buffer(raw, offsets)
        ids, dictionary = canonicalize(ids, values, lossless)
    if stats is not None:
        st["remap_ms"] += remap_ms  # host-side block rewrite rides the phase
        publish_stats(stats, st, ids.shape[0], len(dictionary), t_wall)
    return ids, dictionary


def _ingest_serial(paths, tabs, expect_quad, skip_comments, stats):
    lib = load()
    t_wall = time.perf_counter()
    h = lib.rdf_ingest_new()
    _apply_opts(lib, h)
    try:
        for p in paths:
            with tracer.span("ingest-file", cat=tracer.CAT_STAGE,
                             path=os.path.basename(os.fspath(p))):
                rc = lib.rdf_ingest_file(h, os.fspath(p).encode(), int(tabs),
                                         int(expect_quad), int(skip_comments))
            if rc < 0:
                raise NativeIngestError(
                    lib.rdf_ingest_error(h).decode(errors="replace"))
        with tracer.span("ingest-finalize", cat=tracer.CAT_STAGE):
            n_values = lib.rdf_ingest_finalize(h)
        n_triples = lib.rdf_ingest_num_triples(h)
        ids = np.empty((n_triples, 3), np.int32)
        if n_triples:
            lib.rdf_ingest_get_triples(h, ids.ctypes.data_as(ctypes.c_void_p))
        nbytes = lib.rdf_ingest_values_bytes(h)
        buf = np.empty(max(nbytes, 1), np.uint8)
        offsets = np.empty(n_values + 1, np.int64)
        lib.rdf_ingest_get_values(
            h, buf.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p))
        st = _read_stats(lib, h)
    finally:
        lib.rdf_ingest_free(h)
    values, lossless = _values_from_buffer(buf.tobytes()[:nbytes], offsets)
    ids, dictionary = canonicalize(ids, values, lossless)
    if stats is not None:
        publish_stats(stats, st, ids.shape[0], len(dictionary), t_wall)
    return ids, dictionary


def publish_stats(stats: dict, st: dict, n_triples: int, n_values: int,
                   t_wall: float) -> None:
    """The sanctioned ingest publish shim: finalize the native stats lanes
    and merge them into the caller's ingest dict via the obs registry
    mirror (so bytes/s, triples/s etc. also reach Prometheus exposition).
    Per-phase latencies additionally land in registry histograms
    (``ingest_<phase>_ms``) so tpu_watch --status and the flight recorder
    can tell a wedged ingest from a slow disk."""
    wall_s = max(time.perf_counter() - t_wall, 1e-9)
    st["wall_ms"] = round(wall_s * 1000.0, 1)
    st["triples"] = int(n_triples)
    st["values"] = int(n_values)
    st["triples_per_sec"] = round(n_triples / wall_s, 1)
    st["bytes_per_sec"] = round(st["bytes_read"] / wall_s, 1)
    for k in ("read_ms", "decode_ms", "parse_ms", "intern_ms", "merge_ms",
              "remap_ms", "queue_stall_ms"):
        if k in st:
            st[k] = round(st[k], 2)
            metrics.observe(f"ingest_{k}", st[k])
    metrics.observe("ingest_wall_ms", st["wall_ms"])
    metrics.mutate(stats, lambda c: c.update(st))


def ingest_files(paths, tabs: bool = False, expect_quad: bool = False,
                 skip_comments: bool = True, *, threads: int | None = None,
                 chunk_bytes: int | None = None, stats: dict | None = None):
    """Parse + intern all files natively.  Returns ((N, 3) int32 ids, Dictionary).

    ``threads`` (default: RDFIND_INGEST_THREADS, else all cores) > 1 runs the
    parallel streaming engine; ``1`` restores the serial reference engine.
    Output is bit-identical either way.  ``stats``, when a dict, receives the
    ingest telemetry (bytes/s, triples/s, per-phase ms, thread count, queue
    stalls — see README "Ingest performance").

    Raises NativeIngestError on parse errors (same failure surface as the
    Python parser's ParseError) or if the library is unavailable.
    """
    if load() is None:
        raise NativeIngestError(f"native ingest unavailable: {_lib_error}")
    paths = list(paths)
    n_threads = ingest_threads(threads)
    if n_threads <= 1:
        return _ingest_serial(paths, tabs, expect_quad, skip_comments, stats)
    return _ingest_parallel(paths, tabs, expect_quad, skip_comments,
                            n_threads, chunk_bytes, stats)
