"""Multi-file, gz-aware line reading with file ids and glob resolution.

The analog of MultiFileTextInputFormat (rdfind-flink/.../persistence/
MultiFileTextInputFormat.java:49-368): many input paths, each line tagged with its
file id, .gz files transparently decompressed (gz is unsplittable there too,
:225-230), comment lines (#...) filterable, per-file encodings supported.
"""

from __future__ import annotations

import glob
import gzip
import io
import os
from collections.abc import Iterator


def resolve_path_patterns(patterns) -> list[str]:
    """Expand globs / directories into a sorted file list (RDFind.resolvePathPatterns)."""
    out = []
    for pat in patterns:
        if os.path.isdir(pat):
            out.extend(sorted(
                os.path.join(pat, f) for f in os.listdir(pat)
                if os.path.isfile(os.path.join(pat, f))))
        else:
            matches = sorted(glob.glob(pat))
            if not matches and os.path.isfile(pat):
                matches = [pat]
            if not matches:
                raise FileNotFoundError(f"no input files match {pat!r}")
            out.extend(matches)
    if not out:
        raise FileNotFoundError("no input files")
    return out


def open_text(path: str, encoding: str = "utf-8"):
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"), encoding=encoding,
                                errors="replace")
    return open(path, encoding=encoding, errors="replace")


def iter_lines(paths, skip_comments: bool = True,
               encoding: str = "utf-8") -> Iterator[tuple[int, str]]:
    """Yield (file_id, line) over all files; comment lines (leading '#') skipped."""
    for file_id, path in enumerate(paths):
        with open_text(path, encoding) as f:
            for line in f:
                if skip_comments and line.startswith("#"):
                    continue
                yield file_id, line.rstrip("\n")
