"""Multi-file, gz-aware line reading with file ids and glob resolution.

The analog of MultiFileTextInputFormat (rdfind-flink/.../persistence/
MultiFileTextInputFormat.java:49-368): many input paths, each line tagged with
its file id, .gz files transparently decompressed (gz is unsplittable there
too, :225-230), comment lines (#...) filterable, regex file-name filters
(:76-100,219-231), and per-file encodings with BOM detection (the Encoding
role, rdfind-flink/.../util/Encoding.java:15-156).

``encoding`` accepts:
  * a str — one charset for every file; ``"auto"`` sniffs a BOM per file and
    falls back to UTF-8;
  * a dict — per-file charsets keyed by full path or basename (missing keys
    fall back to the dict's ``None`` entry, then UTF-8);
  * a callable ``path -> charset``.
"""

from __future__ import annotations

import codecs
import glob
import gzip
import io
import os
import re
from collections.abc import Iterator

# Checked in order: UTF-32 BOMs start with the UTF-16 ones, so they go first.
# Mapped to the self-detecting codec names, which strip the BOM on decode.
_BOMS = (
    (codecs.BOM_UTF32_LE, "utf-32"),
    (codecs.BOM_UTF32_BE, "utf-32"),
    (codecs.BOM_UTF8, "utf-8-sig"),
    (codecs.BOM_UTF16_LE, "utf-16"),
    (codecs.BOM_UTF16_BE, "utf-16"),
)


def resolve_path_patterns(patterns, name_filter: str | None = None) -> list[str]:
    """Expand globs / directories into a sorted file list (RDFind.resolvePathPatterns).

    ``name_filter``: regex applied to file basenames, like the reference's
    file-filtered directory scan (MultiFileTextInputFormat.java:76-100).
    """
    out = []
    for pat in patterns:
        if os.path.isdir(pat):
            out.extend(sorted(
                os.path.join(pat, f) for f in os.listdir(pat)
                if os.path.isfile(os.path.join(pat, f))))
        else:
            matches = sorted(glob.glob(pat))
            if not matches and os.path.isfile(pat):
                matches = [pat]
            if not matches:
                raise FileNotFoundError(f"no input files match {pat!r}")
            out.extend(matches)
    if name_filter is not None:
        rx = re.compile(name_filter)
        out = [p for p in out if rx.search(os.path.basename(p))]
    if not out:
        raise FileNotFoundError("no input files"
                                + (f" (after filter {name_filter!r})"
                                   if name_filter else ""))
    return out


def _open_raw(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def input_sizes(paths) -> list[int]:
    """On-disk byte size per input file (0 for unstatable paths).

    Feeds ingest telemetry and the parallel ingest's unit planning:
    uncompressed bytes are what the byte-range chunker splits, while .gz
    sizes only bound file-level parallelism (gz members cannot be
    seek-split, so they always parse as one unit)."""
    out = []
    for p in paths:
        try:
            out.append(os.path.getsize(p))
        except OSError:
            out.append(0)
    return out


def sniff_encoding(path: str, default: str = "utf-8") -> str:
    """Detect a BOM (gz-aware) and return the matching codec; else ``default``."""
    with _open_raw(path) as f:
        head = f.read(4)
    for bom, name in _BOMS:
        if head.startswith(bom):
            return name
    return default


def encoding_for(path: str, encoding) -> str:
    """Resolve the per-file charset from a str/dict/callable spec."""
    if callable(encoding):
        enc = encoding(path)
    elif isinstance(encoding, dict):
        enc = encoding.get(path, encoding.get(os.path.basename(path),
                                              encoding.get(None, "utf-8")))
    else:
        enc = encoding or "utf-8"
    enc = enc or "utf-8"
    if enc == "auto":
        return sniff_encoding(path)
    return enc


def is_utf8(encoding) -> bool:
    """True when ``encoding`` names UTF-8 under any alias (UTF-8, utf8, U8...).

    Gate for the UTF-8-only native ingest.  ``"auto"`` (BOM sniff) and
    dict/callable per-file specs are not statically UTF-8, so they return
    False; unknown codec names also return False rather than raising.
    """
    if not isinstance(encoding, str) or encoding == "auto":
        return False
    try:
        return codecs.lookup(encoding).name == "utf-8"
    except LookupError:
        return False


def open_text(path: str, encoding="utf-8"):
    enc = encoding_for(path, encoding)
    return io.TextIOWrapper(_open_raw(path), encoding=enc, errors="replace")


def iter_lines(paths, skip_comments: bool = True,
               encoding="utf-8") -> Iterator[tuple[int, str]]:
    """Yield (file_id, line) over all files; comment lines (leading '#') skipped."""
    for file_id, path in enumerate(paths):
        with open_text(path, encoding) as f:
            for line in f:
                if skip_comments and line.startswith("#"):
                    continue
                yield file_id, line.rstrip("\n")
