"""N-Triples / N-Quads line parsing.

Replaces the reference's rdf-converter NTriplesParser/NQuadsParser dependency
(RDFind.scala:219-237): each line yields 3 raw term tokens (subject, predicate,
object); N-Quads' 4th term (graph) is parsed and dropped, like the reference which
only keeps fields 0..2.  Tokens keep their surface syntax (<iri>, _:blank,
"literal"^^<type>, "literal"@lang) — CIND discovery only needs consistent equality,
and keeping tokens verbatim is lossless.

A tab-separated mode mirrors the reference's --tabs flag (NTriplesParser('\\t')).
"""

from __future__ import annotations


class ParseError(ValueError):
    pass


def _scan_term(line: str, i: int, n: int) -> tuple[str, int]:
    """Scan one term starting at non-space position i; returns (token, next_index)."""
    c = line[i]
    if c == "<":  # IRI
        j = line.find(">", i + 1)
        if j < 0:
            raise ParseError(f"unterminated IRI: {line!r}")
        return line[i:j + 1], j + 1
    if c == '"':  # literal with escapes, optional @lang / ^^<dtype>
        j = i + 1
        while j < n:
            if line[j] == "\\":
                j += 2
                continue
            if line[j] == '"':
                break
            j += 1
        if j >= n:
            raise ParseError(f"unterminated literal: {line!r}")
        j += 1  # past closing quote
        if j < n and line[j] == "@":  # language tag
            while j < n and line[j] not in " \t":
                j += 1
        elif line.startswith("^^", j):
            j += 2
            if j < n and line[j] == "<":
                k = line.find(">", j + 1)
                if k < 0:
                    raise ParseError(f"unterminated datatype IRI: {line!r}")
                j = k + 1
        return line[i:j], j
    # blank node or other token: read to whitespace
    j = i
    while j < n and line[j] not in " \t":
        j += 1
    return line[i:j], j


def parse_line(line: str, expect_quad: bool = False) -> tuple[str, str, str] | None:
    """Parse one N-Triples (or N-Quads) line into (s, p, o); None for blank lines."""
    n = len(line)
    i = 0
    terms = []
    while i < n and len(terms) < (4 if expect_quad else 3):
        while i < n and line[i] in " \t":
            i += 1
        if i >= n or line[i] == ".":
            break
        tok, i = _scan_term(line, i, n)
        terms.append(tok)
    if not terms:
        return None
    if len(terms) < 3:
        raise ParseError(f"expected 3 terms, got {len(terms)}: {line!r}")
    return terms[0], terms[1], terms[2]


def parse_tab_line(line: str) -> tuple[str, str, str] | None:
    """Tab-separated triple line (--tabs mode)."""
    if not line.strip():
        return None
    parts = line.rstrip("\r\n").split("\t")
    if len(parts) < 3:
        raise ParseError(f"expected 3 tab-separated fields: {line!r}")
    return parts[0], parts[1], parts[2]
