"""Device mesh helpers.

A 1-D data mesh is the core topology for CIND discovery (the workload is batch
dataflow, not tensor algebra): every exchange is value- or capture-hash bucketed
all_to_all over the single axis, which XLA lowers to ICI collectives within a slice
and DCN across slices.  Mirrors the role of StratosphereParameters'
degree-of-parallelism + executor config (rdfind-util/.../StratosphereParameters.
java:35-154).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

AXIS = "d"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the first `n_devices` available devices (all by default)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available")
        devices = devices[:n_devices]
    import numpy as np

    return Mesh(np.asarray(devices), (AXIS,))
