"""Device mesh helpers, single- and multi-host.

A 1-D data mesh is the core topology for CIND discovery (the workload is batch
dataflow, not tensor algebra): every exchange is value- or capture-hash bucketed
all_to_all over the single axis, which XLA lowers to ICI collectives within a slice
and DCN across slices.  Mirrors the role of StratosphereParameters'
degree-of-parallelism + executor config (rdfind-util/.../StratosphereParameters.
java:35-154).

Multi-host: `initialize_multihost` wires JAX's distributed runtime (the
DCN-analog of the reference's multi-node Flink runtime — JobManager RPC +
netty shuffles, pom.xml:33 / StratosphereParameters.java:68-122), after which
`make_mesh()` spans every process's devices and the sharded pipelines' host
orchestration reads global state via `host_gather`.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

AXIS = "d"


_MULTIHOST_INITIALIZED = False


def initialize_multihost(coordinator: str, num_processes: int,
                         process_id: int, *,
                         shutdown_timeout_seconds: int = 7200) -> None:
    """Join this process to a multi-host run (idempotent per process).

    `coordinator` is `host:port` of process 0.  Must be called before any
    other jax API touches the backend.

    shutdown_timeout_seconds raises jax's default 300 s exit barrier: hosts
    finish batch phases minutes apart when compute is uneven (or, on the CPU
    minicluster, when one core timeshares every "device"), and a host that
    exits first must wait at the barrier instead of tearing the runtime down
    under its peers (observed: an 8M-triple 2-process run lost host 0 to the
    default barrier while it was still in its final phase).
    """
    global _MULTIHOST_INITIALIZED
    # NB: probing via jax.process_count() would itself initialize the XLA
    # backend and make initialize() illegal — use the distributed-state API.
    if _MULTIHOST_INITIALIZED or jax.distributed.is_initialized():
        return  # already joined (jax.distributed.initialize is once-only)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id,
                               shutdown_timeout_seconds=shutdown_timeout_seconds)
    _MULTIHOST_INITIALIZED = True


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the first `n_devices` available devices (all by default).

    Under a multi-host runtime `jax.devices()` spans every process, so the
    mesh does too.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def host_gather(x) -> np.ndarray:
    """Device output -> host numpy, valid on every process.

    Single-process: a plain transfer.  Multi-process: shard_map outputs over
    P(AXIS) are globally sharded and not fully addressable from one host, so
    gather them with process_allgather (one DCN collective).
    """
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def make_global(host_array: np.ndarray, mesh: Mesh) -> jax.Array:
    """A global row-sharded device array from an identical-on-every-host
    numpy array (rows divide evenly by the mesh size).

    Single-process this is a plain device put; multi-process each host
    donates only the rows its devices own.
    """
    sharding = NamedSharding(mesh, P(AXIS) if host_array.ndim == 1
                             else P(AXIS, *([None] * (host_array.ndim - 1))))
    if jax.process_count() == 1:
        return jax.device_put(host_array, sharding)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx])
