"""Device mesh helpers, single- and multi-host.

A 1-D data mesh is the core topology for CIND discovery (the workload is batch
dataflow, not tensor algebra): every exchange is value- or capture-hash bucketed
all_to_all over the single axis, which XLA lowers to ICI collectives within a slice
and DCN across slices.  Mirrors the role of StratosphereParameters'
degree-of-parallelism + executor config (rdfind-util/.../StratosphereParameters.
java:35-154).

Multi-host: `initialize_multihost` wires JAX's distributed runtime (the
DCN-analog of the reference's multi-node Flink runtime — JobManager RPC +
netty shuffles, pom.xml:33 / StratosphereParameters.java:68-122), after which
`make_mesh()` spans every process's devices and the sharded pipelines' host
orchestration reads global state via `host_gather`.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

AXIS = "d"


def topology_hosts(num_dev: int) -> int:
    """How many hosts the `num_dev`-device mesh spans, for ledger attribution
    and the hierarchical factorization.

    `RDFIND_HIER_HOSTS` overrides the runtime's process count — that is how
    single-process tests (8 fake CPU devices) and benches model a 2-host pod
    proxy.  A host count that does not divide the mesh degenerates to 1
    (every device "local"): the hierarchical path requires an even
    (hosts x local) factorization of the axis.
    """
    try:
        hosts = int(os.environ.get("RDFIND_HIER_HOSTS", "") or
                    jax.process_count())
    except ValueError:
        hosts = jax.process_count()
    if hosts < 1 or num_dev % hosts != 0:
        return 1
    return hosts


def hier_spec(num_dev: int):
    """Resolve `RDFIND_HIER_EXCHANGE` to a (hosts, local_devices) factorization
    of the 1-D axis, or None for the flat single-hop exchange.

    auto (default) -- hierarchical only when the mesh spans >1 host (flat is
    strictly cheaper on one host: the two-level path moves every row twice);
    1 -- force hierarchical even single-host (tests / benches exercise the
    path via `RDFIND_HIER_HOSTS`); 0 -- force the flat path exactly.
    """
    knob = os.environ.get("RDFIND_HIER_EXCHANGE", "auto").strip().lower()
    if knob in ("0", "off", "flat"):
        return None
    hosts = topology_hosts(num_dev)
    if knob in ("1", "on", "force"):
        return (hosts, num_dev // hosts)
    if hosts <= 1:  # auto
        return None
    return (hosts, num_dev // hosts)


def dcn_chunks() -> int:
    """`RDFIND_HIER_DCN_CHUNKS`: split the inter-host hop of a hierarchical
    exchange into this many independent all_to_all slices of the capacity
    axis (overlap food for the dispatch-ahead executor).  1 = one collective;
    ``auto`` picks from the last measured overlap report (ROADMAP item 3 —
    see :func:`dcn_chunks_auto`).
    """
    knob = os.environ.get("RDFIND_HIER_DCN_CHUNKS", "1").strip().lower()
    if knob == "auto":
        from ..obs import metrics

        return dcn_chunks_auto(metrics.registry().get("overlap"))
    try:
        return max(1, int(knob or "1"))
    except ValueError:
        return 1


def dcn_chunks_auto(report) -> int:
    """Chunk count from a measured overlap report (the DispatchStats
    `overlap_report` dict the executor publishes under the "overlap" key).

    The heuristic reads `overlap_efficiency` — where the measured wall sat
    between the perfect-overlap and fully-serial bounds on the LAST run:

    * no report yet / no pulls worth hiding (pull_ms < 1 ms) -> 1 — there is
      nothing for extra chunks to overlap, and each chunk adds a collective's
      fixed latency;
    * efficiency >= 0.85 -> 1 — the dispatch-ahead executor is already
      hiding the pulls; splitting the hop only adds launch overhead;
    * efficiency >= 0.5 -> 2 — partial overlap: halving the hop gives the
      executor a second slice to hide behind compute;
    * below 0.5 -> 4 — the DCN hop dominates the critical path; finer
      slices are the only overlap food available (4 keeps per-slice payloads
      well above the latency floor; going finer has measured negative).

    Deliberately one-shot (reads the previous run, steers the next) rather
    than a controller: exchange walls are noisy at small scale and a stable
    knob beats a hunting one.
    """
    if not isinstance(report, dict):
        return 1
    eff = report.get("overlap_efficiency")
    pull_ms = report.get("pull_ms") or 0.0
    if eff is None or pull_ms < 1.0:
        return 1
    if eff >= 0.85:
        return 1
    if eff >= 0.5:
        return 2
    return 4


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable shard_map: the ONE entry point for every sharded
    program in this repo.

    Newer jax exposes `jax.shard_map` with the replication check named
    `check_vma`; 0.4.x only has `jax.experimental.shard_map.shard_map` with
    the same flag named `check_rep`.  The pipelines disable the check either
    way (their collective programs trip its conservative replication
    inference), so the flag just needs to reach whichever spelling exists.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def link_probe_enabled() -> bool:
    """Whether the startup link-capability probe is armed
    (RDFIND_LINK_PROBE=1).  Off by default: it costs a few tiny collectives
    at pipeline start; the exchange timers degrade gracefully without it
    (achieved GB/s is still reported, utilization-of-peak is not)."""
    return os.environ.get("RDFIND_LINK_PROBE", "") not in ("", "0")


_LINK_PROBE_KEY = None  # (num_dev, hosts) the cached probe ran under

_PROBE_CAP = 1 << 13  # rows per buffer lane: tiny (KBs-MBs), but a full
_PROBE_REPS = 3       # fixed-shape collective through the real links


def _time_a2a(mesh, group: int, cap: int, groups) -> float:
    """Achieved GB/s of one all_to_all over `groups` (ledger byte
    convention: every participating device moves its whole (group, cap)
    int32 buffer, self-rows included — matching exchange_split_bytes so
    utilization-of-peak compares like with like)."""
    import time

    from .exchange import _a2a

    num_dev = int(mesh.devices.size)
    spec = P(AXIS, None)
    data = make_global(
        np.zeros((num_dev * group, cap), np.int32), mesh)
    fn = jax.jit(shard_map(
        lambda b: _a2a(b, AXIS, groups),
        mesh=mesh, in_specs=spec, out_specs=spec))
    jax.block_until_ready(fn(data))  # compile + warm the route
    t0 = time.perf_counter()
    for _ in range(_PROBE_REPS):
        data = fn(data)
    jax.block_until_ready(data)
    dt = (time.perf_counter() - t0) / _PROBE_REPS
    nbytes = num_dev * group * cap * 4
    return nbytes / max(dt, 1e-9) / 1e9


def link_probe(mesh=None, force: bool = False) -> dict:
    """One-shot per-hop link-capability microbench, cached in the metrics
    registry (obs/metrics.link_caps).

    Runs a tiny fixed-shape all_to_all per hop of the current topology —
    intra-host groups (ICI; the full mesh when single-host) and, when the
    (hosts x local) factorization exists, inter-host groups (DCN) — and
    records achieved GB/s as the measured peak each exchange timer
    normalizes against.  Idempotent per (num_dev, hosts): the sharded
    pipeline calls maybe_link_probe at init and only the first call pays.
    """
    global _LINK_PROBE_KEY
    from ..obs import metrics, tracer

    if mesh is None:
        mesh = make_mesh()
    num_dev = int(mesh.devices.size)
    hosts = topology_hosts(num_dev)
    key = (num_dev, hosts)
    if not force and _LINK_PROBE_KEY == key:
        return metrics.link_caps()
    from .exchange import hier_groups

    caps = {"num_dev": num_dev, "hosts": hosts, "probe_cap": _PROBE_CAP}
    if hosts > 1:
        intra, inter = hier_groups((hosts, num_dev // hosts))
        caps["ici_gbps"] = round(
            _time_a2a(mesh, num_dev // hosts, _PROBE_CAP, intra), 3)
        caps["dcn_gbps"] = round(
            _time_a2a(mesh, hosts, _PROBE_CAP, inter), 3)
    else:
        caps["ici_gbps"] = round(
            _time_a2a(mesh, num_dev, _PROBE_CAP, None), 3)
    _LINK_PROBE_KEY = key
    metrics.set_link_caps(caps)
    tracer.instant("link_probe", cat=tracer.CAT_EXCHANGE, **caps)
    return caps


def maybe_link_probe(mesh=None) -> dict:
    """link_probe when armed (the pipeline-init call site); {} otherwise."""
    if not link_probe_enabled():
        return {}
    return link_probe(mesh)


_MULTIHOST_INITIALIZED = False


def initialize_multihost(coordinator: str, num_processes: int,
                         process_id: int, *,
                         shutdown_timeout_seconds: int = 7200) -> None:
    """Join this process to a multi-host run (idempotent per process).

    `coordinator` is `host:port` of process 0.  Must be called before any
    other jax API touches the backend.

    shutdown_timeout_seconds raises jax's default 300 s exit barrier: hosts
    finish batch phases minutes apart when compute is uneven (or, on the CPU
    minicluster, when one core timeshares every "device"), and a host that
    exits first must wait at the barrier instead of tearing the runtime down
    under its peers (observed: an 8M-triple 2-process run lost host 0 to the
    default barrier while it was still in its final phase).
    """
    global _MULTIHOST_INITIALIZED
    # NB: probing via jax.process_count() would itself initialize the XLA
    # backend and make initialize() illegal — use the distributed-state API.
    # (is_initialized() is newer jax; 0.4.x readers go through global_state.)
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is None:
        from jax._src import distributed as _dist

        def is_init():
            return _dist.global_state.client is not None
    if _MULTIHOST_INITIALIZED or is_init():
        return  # already joined (jax.distributed.initialize is once-only)
    try:
        # Multi-process CPU backends need an explicit cross-process
        # collectives implementation on some jax versions ("Multiprocess
        # computations aren't implemented on the CPU backend" otherwise);
        # gloo is the TCP one.  No effect on TPU clients.  NB the flag is
        # not always readable as a config attribute — update() is the only
        # portable accessor, so only an explicit non-default survives.
        cur = getattr(jax.config, "jax_cpu_collectives_implementation", None)
        if cur in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # versions without the flag don't need it
    kwargs = dict(coordinator_address=coordinator,
                  num_processes=num_processes, process_id=process_id)
    extra = dict(shutdown_timeout_seconds=shutdown_timeout_seconds,
                 **_init_timeout_kwargs())
    while True:
        try:
            jax.distributed.initialize(**kwargs, **extra)
            break
        except TypeError:
            # Older jax predates one of the knobs (exit barrier /
            # per-attempt init timeout): drop them one at a time — joining
            # with defaults beats not joining at all.
            if not extra:
                raise
            extra.popitem()
    _MULTIHOST_INITIALIZED = True


def ensure_distributed(coordinator: str, num_processes: int,
                       process_id: int, *,
                       shutdown_timeout_seconds: int = 7200) -> int:
    """`initialize_multihost` with bounded retry + seeded exponential
    backoff around the rendezvous — the product-level form of the retry
    the two-process test tier used to carry in-test.

    The gloo TCP rendezvous wedges nondeterministically on loaded CI boxes
    (observed ~9-minute burns before an external retry rescued the run);
    here each attempt is bounded by the distributed runtime's own
    initialization timeout (RDFIND_INIT_TIMEOUT_S where the jax version
    accepts it) under a watchdog deadman, failures back off with the fault
    ladder's jittered schedule, and RDFIND_INIT_RETRIES (default 3)
    attempts are made before giving up.  Returns the number of retries
    used (0 = first attempt joined), published as
    ``distributed_init_retries`` in the metrics registry.

    Single-process callers (num_processes <= 1) are a no-op returning 0.
    """
    from ..obs import metrics
    from ..runtime import faults, watchdog

    if num_processes <= 1:
        return 0
    tries = max(1, int(os.environ.get("RDFIND_INIT_RETRIES", "3")))
    last: Exception | None = None
    for attempt in range(tries):
        try:
            with watchdog.collective("init", force=True):
                initialize_multihost(
                    coordinator, num_processes, process_id,
                    shutdown_timeout_seconds=shutdown_timeout_seconds)
            metrics.gauge_set(None, "distributed_init_retries", attempt)
            return attempt
        except (faults.Preempted, faults.FallbackRequired):
            raise
        except Exception as e:
            last = e
            _teardown_distributed()
            if attempt == tries - 1:
                break
            delay_ms = faults._backoff_ms(attempt)
            print(f"rdfind: distributed init attempt {attempt + 1}/{tries} "
                  f"failed ({e}); retrying after {delay_ms:.0f} ms",
                  file=__import__("sys").stderr, flush=True)
            import time as _time

            _time.sleep(delay_ms / 1e3)
    metrics.gauge_set(None, "distributed_init_retries", tries - 1)
    raise RuntimeError(
        f"distributed init failed after {tries} attempts") from last


def _teardown_distributed() -> None:
    """Best-effort shutdown between init retries: jax.distributed.initialize
    is once-only per live client, so a failed rendezvous must release its
    half-open state before the next attempt."""
    global _MULTIHOST_INITIALIZED
    _MULTIHOST_INITIALIZED = False
    try:
        jax.distributed.shutdown()
    except Exception:
        pass


def _init_timeout_kwargs() -> dict:
    """initialization_timeout for jax versions that accept it: bounds one
    rendezvous attempt so ensure_distributed's retry loop gets control
    back (RDFIND_INIT_TIMEOUT_S; 0/unset keeps jax's default)."""
    try:
        t = float(os.environ.get("RDFIND_INIT_TIMEOUT_S", "0"))
    except ValueError:
        t = 0.0
    return {"initialization_timeout": int(t)} if t > 0 else {}


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D mesh over the first `n_devices` available devices (all by default).

    Under a multi-host runtime `jax.devices()` spans every process, so the
    mesh does too.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def _host_gather_raw(x) -> np.ndarray:
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def host_gather(x) -> np.ndarray:
    """Device output -> host numpy, valid on every process.

    Single-process: a plain transfer.  Multi-process: shard_map outputs over
    P(AXIS) are globally sharded and not fully addressable from one host, so
    gather them with process_allgather (one DCN collective).

    Pulls are pure reads of device state, so every gather rides the fault
    gate + bounded backoff retry of runtime/faults.guarded_pull (the
    `host_pull` injection site; RDFIND_STRICT=1 fails fast).
    """
    from ..runtime import faults

    return faults.guarded_pull(lambda: _host_gather_raw(x))


def host_gather_many(xs) -> list:
    """Batched host_gather: ONE blocking round trip for a list of arrays.

    Single-process, a single device_get drains every pending transfer at once
    (pair it with dispatch.stage_to_host so the copies were already in
    flight).  Multi-process each array still needs its own allgather
    collective, but issuing them back-to-back keeps the DCN pipe busy.
    Counts as ONE host_pull fault-site hit either way (one round trip).
    """
    from ..runtime import faults

    xs = list(xs)
    if jax.process_count() == 1:
        return faults.guarded_pull(lambda: jax.device_get(xs))
    return faults.guarded_pull(lambda: [_host_gather_raw(x) for x in xs])


def allgather_host_values(values, site: str = "allgather") -> np.ndarray:
    """(n_hosts, k) matrix of per-host floats: one tiny DCN allgather under
    a multi-process runtime, the identity single-process.

    The skew meter rides this each committed pass (per-host wall + phase
    breakdown are HOST-side clocks, so they cannot fuse into the device
    telemetry lanes) — the payload is a handful of float64s, noise next to
    the pass's own counter pull.

    `site` names the caller for the collective watchdog (and the
    wedge@<site> fault family): the deadman is armed around the gather, so
    a peer that never answers becomes a recoverable preemption instead of
    an indefinite block.
    """
    from ..runtime import watchdog

    arr = np.asarray(values, np.float64).reshape(1, -1)
    with watchdog.collective(site, arr.nbytes * jax.process_count()):
        if jax.process_count() == 1:
            return arr
        from jax.experimental import multihost_utils

        out = np.asarray(multihost_utils.process_allgather(arr))
    return out.reshape(-1, arr.shape[1])


def make_global(host_array: np.ndarray, mesh: Mesh) -> jax.Array:
    """A global row-sharded device array from an identical-on-every-host
    numpy array (rows divide evenly by the mesh size).

    Single-process this is a plain device put; multi-process each host
    donates only the rows its devices own.
    """
    sharding = NamedSharding(mesh, P(AXIS) if host_array.ndim == 1
                             else P(AXIS, *([None] * (host_array.ndim - 1))))
    if jax.process_count() == 1:
        return jax.device_put(host_array, sharding)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx])
