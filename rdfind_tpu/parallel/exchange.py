"""Fixed-capacity bucket exchange + sorted-join primitives (inside shard_map).

The reference shuffles variable-sized record streams over TCP (Flink hash shuffles,
custom Partitioners — operators/LoadBasedPartitioner.scala:13-52,
JoinLineRebalancePartitioner.scala:11-20).  On TPU, collectives move *fixed-shape*
buffers, so a shuffle becomes: sort rows by destination bucket, scatter into a
(D, capacity) send buffer, one tiled all_to_all, and a validity mask derived from the
SENTINEL fill.  Overflowing rows are counted (never silently dropped without notice):
callers must check the psum'd overflow count and retry with a larger capacity.

Round trips: all_to_all with split_axis=0/concat_axis=0 is slot-preserving —
received row (src, k) on the owner came from src's send slot (owner, k) — so a
reply column pushed back through the same collective lands exactly in the
sender's send-buffer slots.  `route` exposes that slot mapping and `route_reply`
rides it; `global_row_counts` uses the pair to implement the distributed
group-by-count-join-back that powers the sharded frequency filter (the
reference's broadcast Bloom-filter pruning, FrequentConditionPlanner.scala:
201-283, recast as exact counts flowing back to the asking rows).

All functions assume they run inside shard_map over a 1-D mesh axis.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import metrics, tracer
from ..ops import hashing, segments

SENTINEL = segments.SENTINEL


def exchange_volume_bytes(num_dev: int, capacity: int, lanes: int) -> int:
    """Global wire bytes of ONE fixed-shape collective at this site.

    Every lane is a (D, capacity) int32 buffer per device, moved whole
    regardless of how many rows are valid (that is the fixed-shape contract:
    all_to_all and all_gather volume is static).  Globally that is
    D devices x D destination rows x capacity x 4 bytes per lane.
    """
    return int(num_dev) * int(num_dev) * int(capacity) * int(lanes) * 4


def log_exchange(stats, site: str, *, num_dev: int, capacity: int,
                 lanes: int, calls: int = 1, rows: int | None = None,
                 retries: int = 0) -> None:
    """Host-side ledger of one exchange site's communication volume.

    The device collectives are fixed-shape, so the moved bytes are fully
    determined by (num_dev, capacity, lanes) x calls — the host callers that
    plan the capacities record every dispatch here (including retried and
    optimistically-discarded ones: their buffers moved too).  `rows`, when
    the host knows it, records measured valid rows; `rows_capacity` is the
    buffer-row upper bound the volume was provisioned for.  Multi-chip
    bandwidth projections divide `bytes` by the interconnect's measured
    throughput (VERDICT r5 #5).
    """
    if stats is None:
        return
    nbytes = calls * exchange_volume_bytes(num_dev, capacity, lanes)

    def fn(c):
        e = c.setdefault("exchange_sites", {}).setdefault(
            site, dict(calls=0, capacity=0, lanes=lanes, bytes=0,
                       rows_capacity=0, rows=0, overflow_retries=0))
        e["calls"] += calls
        e["capacity"] = max(e["capacity"], int(capacity))
        e["lanes"] = lanes
        e["bytes"] += nbytes
        e["rows_capacity"] += calls * int(num_dev) * int(capacity)
        if rows is not None:
            e["rows"] += int(rows)
        e["overflow_retries"] += retries

    metrics.mutate(stats, fn, key="exchange_sites", kind=metrics.STRUCT)
    tracer.instant("exchange", cat=tracer.CAT_EXCHANGE, site=site,
                   calls=calls, capacity=int(capacity), bytes=nbytes)


def log_exchange_retry(stats, site: str) -> None:
    """Count one overflow-retry against `site` (ledger entry created lazily
    so a retry before the first successful dispatch still lands)."""
    if stats is None:
        return

    def fn(c):
        e = c.setdefault("exchange_sites", {}).setdefault(
            site, dict(calls=0, capacity=0, lanes=0, bytes=0,
                       rows_capacity=0, rows=0, overflow_retries=0))
        e["overflow_retries"] += 1

    metrics.mutate(stats, fn, key="exchange_sites", kind=metrics.STRUCT)
    tracer.instant("exchange_retry", cat=tracer.CAT_EXCHANGE, site=site)


def pack_counters(values):
    """Fuse scalar counters into ONE int32 lane array (device side).

    The sharded pass programs used to return overflow flags and tail counters
    as separate outputs, each costing its own blocking host_gather round trip
    per pass.  Packing every psum'd scalar into a single (K,) lane means the
    host reads ALL of a pass's control state in one (async-stageable) pull —
    the per-pass sync-count contract of the pipelined executor.
    """
    return jnp.stack([jnp.asarray(v, jnp.int32) for v in values])


def unpack_counters(host_arr, n: int, num_dev: int) -> np.ndarray:
    """Host inverse of pack_counters over a P(AXIS)-gathered output.

    Every lane is globally reduced (psum/pmax) on device, so all devices
    carry identical copies; device 0's row is the answer.
    """
    return np.asarray(host_arr).reshape(num_dev, n)[0]


@dataclasses.dataclass
class RouteState:
    """Slot mapping of one routed exchange (everything route_reply needs)."""

    perm: jnp.ndarray  # sorted order -> original row index
    flat: jnp.ndarray  # per sorted row: slot in the (D*capacity) send buffer
    ok: jnp.ndarray    # per sorted row: survived (valid and under capacity)
    num_dev: int
    capacity: int


def route(cols, valid, bucket, axis_name: str, capacity: int):
    """Route rows to the device equal to their bucket id.

    cols     -- list of (N,) int32 columns (row payload; SENTINEL is reserved);
    valid    -- (N,) bool;
    bucket   -- (N,) int32 destination device in [0, D);
    capacity -- static per-destination row budget.

    Returns (out_cols, out_valid, overflow, state): out_cols are (D*capacity,)
    columns of rows received by this device (garbage where ~out_valid); overflow
    is the global number of rows dropped for exceeding a bucket capacity; state
    feeds route_reply for sending per-received-row answers back.
    """
    d = jax.lax.psum(1, axis_name)
    n = cols[0].shape[0]
    tgt = jnp.where(valid, bucket, d)  # invalid rows to a virtual overflow bucket
    perm = segments.lexsort([tgt])
    t_s = tgt[perm]
    v_s = valid[perm]
    # Position of each row within its destination group.
    idx = jnp.arange(n, dtype=jnp.int32)
    starts = segments.run_starts([t_s])
    run_start = jax.lax.cummax(jnp.where(starts, idx, 0))
    pos = idx - run_start
    ok = v_s & (pos < capacity)
    flat = jnp.where(ok, t_s * capacity + pos, d * capacity)  # OOB => dropped
    overflow_local = (v_s & ~ok).sum()
    overflow = jax.lax.psum(overflow_local, axis_name)

    out_cols = []
    for c in cols:
        buf = jnp.full(d * capacity, SENTINEL, jnp.int32)
        buf = buf.at[flat].set(c[perm], mode="drop")
        buf = buf.reshape(d, capacity)
        recv = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                                  tiled=True)
        out_cols.append(recv.reshape(-1))

    # Validity travels as its own lane so payload SENTINELs stay representable.
    # NB: `ok` is already in sorted order (aligned with `flat`), unlike the
    # payload columns above which are in original order — indexing it with
    # `perm` again would sample validity from unrelated rows and silently drop
    # rows whenever the valid mask is not a compacted prefix.
    vbuf = jnp.zeros(d * capacity, jnp.int32).at[flat].set(
        ok.astype(jnp.int32), mode="drop").reshape(d, capacity)
    recv_v = jax.lax.all_to_all(vbuf, axis_name, split_axis=0, concat_axis=0,
                                tiled=True)
    state = RouteState(perm=perm, flat=flat, ok=ok, num_dev=d, capacity=capacity)
    return out_cols, recv_v.reshape(-1) == 1, overflow, state


def route_reply(answer, state: RouteState, axis_name: str):
    """Send one (D*capacity,) int32 answer-per-received-row back to the senders.

    Returns an (N,) column in the *original row order* of the route() call; rows
    that were dropped (overflow) or invalid get 0.
    """
    n = state.perm.shape[0]
    buf = answer.reshape(state.num_dev, state.capacity)
    back = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                              tiled=True).reshape(-1)
    safe = jnp.clip(state.flat, 0, state.num_dev * state.capacity - 1)
    vals = jnp.where(state.ok, back[safe], 0)
    return jnp.zeros(n, jnp.int32).at[state.perm].set(vals)


def bucket_exchange(cols, valid, bucket, axis_name: str, capacity: int):
    """route() without the reply half (the one-way shuffle)."""
    out_cols, out_valid, overflow, _ = route(cols, valid, bucket, axis_name,
                                             capacity)
    return out_cols, out_valid, overflow


def global_row_counts(key_cols, valid, axis_name: str, capacity: int, *,
                      seed: int):
    """Per-row GLOBAL count of the row's key across all devices.

    Combiner-tree + join-back in one primitive: local distinct keys carry their
    local multiplicities to the key's hash owner (one all_to_all of *distinct*
    keys, not rows), the owner sums them, and the sums ride the reply collective
    back to every asking row.  Exchange volume is O(local distinct keys).

    Returns (counts, overflow): counts is (N,) int32, 0 for invalid rows;
    overflow > 0 means `capacity` was too small and counts are unusable.
    """
    d = jax.lax.psum(1, axis_name)
    u_cols, u_valid, inv, _ = segments.masked_unique(key_cols, valid)
    m = u_cols[0].shape[0]
    inv_safe = jnp.clip(inv, 0, m - 1)
    local_mult = jax.ops.segment_sum(valid.astype(jnp.int32), inv_safe,
                                     num_segments=m)
    bucket = hashing.bucket_of(u_cols, d, seed=seed)
    recv, recv_valid, overflow, state = route(u_cols + [local_mult], u_valid,
                                              bucket, axis_name, capacity)
    g = segments.masked_weighted_row_counts(recv[:-1], recv[-1], recv_valid)
    ans_per_distinct = route_reply(g, state, axis_name)
    return jnp.where(valid, ans_per_distinct[inv_safe], 0), overflow


def global_distinct_frequent(key_cols, valid, min_support, axis_name: str,
                             capacity: int, *, seed: int):
    """GLOBAL number of distinct keys occurring >= min_support times.

    The distributed form of the --find-only-fcs report (the reference counts
    its frequent-condition Bloom filters cluster-wide): local distinct keys
    carry local multiplicities to their hash owner, the owner sums and counts
    its frequent keys, and a psum totals the owners.  Returns (count,
    overflow); overflow > 0 invalidates the count (grow `capacity`).
    """
    d = jax.lax.psum(1, axis_name)
    u_cols, u_valid, inv, _ = segments.masked_unique(key_cols, valid)
    m = u_cols[0].shape[0]
    inv_safe = jnp.clip(inv, 0, m - 1)
    local_mult = jax.ops.segment_sum(valid.astype(jnp.int32), inv_safe,
                                     num_segments=m)
    bucket = hashing.bucket_of(u_cols, d, seed=seed)
    recv, recv_valid, overflow, _ = route(u_cols + [local_mult], u_valid,
                                          bucket, axis_name, capacity)
    g = segments.masked_weighted_row_counts(recv[:-1], recv[-1], recv_valid)
    ok = recv_valid & (g >= min_support)
    _, _, _, n_u = segments.masked_unique(recv[:-1], ok)
    return jax.lax.psum(n_u, axis_name), overflow


def sorted_join_counts(table_cols, table_counts, table_valid, query_cols, query_valid):
    """For each query row, the count of its key in a distinct-key table (0 if absent).

    Both sides are lists of int32 key columns of fixed shapes.  Implemented as a
    tag-sorted merge join: concatenate [table rows (tag 0), query rows (tag 1)],
    lexsort by (key..., tag); each run starts with the table row (if present), whose
    count forward-fills to the run's query rows.
    """
    nt = table_cols[0].shape[0]
    nq = query_cols[0].shape[0]
    tag = jnp.concatenate([jnp.zeros(nt, jnp.int32), jnp.ones(nq, jnp.int32)])
    allv = jnp.concatenate([table_valid, query_valid])
    keys = [
        jnp.where(allv, jnp.concatenate([t, q]), SENTINEL)
        for t, q in zip(table_cols, query_cols)
    ]
    cnt = jnp.concatenate([table_counts, jnp.zeros(nq, jnp.int32)])

    perm = segments.lexsort(keys + [tag])
    keys_s = [k[perm] for k in keys]
    tag_s = tag[perm]
    cnt_s = cnt[perm]
    idx = jnp.arange(nt + nq, dtype=jnp.int32)
    starts = segments.run_starts(keys_s)
    run_start = jax.lax.cummax(jnp.where(starts, idx, 0))
    cnt_at_start = cnt_s[run_start]
    tag_at_start = tag_s[run_start]
    filled = jnp.where(tag_at_start == 0, cnt_at_start, 0)

    # Scatter back to query order: positions of query rows in the concat array.
    out = jnp.zeros(nt + nq, jnp.int32).at[perm].set(filled)
    return out[nt:]
