"""Fixed-capacity bucket exchange + sorted-join primitives (inside shard_map).

The reference shuffles variable-sized record streams over TCP (Flink hash shuffles,
custom Partitioners — operators/LoadBasedPartitioner.scala:13-52,
JoinLineRebalancePartitioner.scala:11-20).  On TPU, collectives move *fixed-shape*
buffers, so a shuffle becomes: sort rows by destination bucket, scatter into a
(D, capacity) send buffer, one tiled all_to_all, and a validity mask derived from the
SENTINEL fill.  Overflowing rows are counted (never silently dropped without notice):
callers must check the psum'd overflow count and retry with a larger capacity.

Round trips: all_to_all with split_axis=0/concat_axis=0 is slot-preserving —
received row (src, k) on the owner came from src's send slot (owner, k) — so a
reply column pushed back through the same collective lands exactly in the
sender's send-buffer slots.  `route` exposes that slot mapping and `route_reply`
rides it; `global_row_counts` uses the pair to implement the distributed
group-by-count-join-back that powers the sharded frequency filter (the
reference's broadcast Bloom-filter pruning, FrequentConditionPlanner.scala:
201-283, recast as exact counts flowing back to the asking rows).

Hierarchical (pod-scale) mode: the reference survives network-bound phases by
combining before the shuffle (Flink combiners ahead of every hash exchange);
the flat all_to_all here makes no ICI/DCN distinction and pays full cross-host
bandwidth for traffic that is mostly intra-host combinable.  With a
(hosts x local_devices) factorization (`mesh.hier_spec`), `route` runs the
shuffle as two hops — intra-host all_to_all (ICI) into a relay slot layout,
then one inter-host exchange (DCN) — with the slot math arranged so the
receive-side layout is bit-identical to the flat path.  `route_combined` adds
the combiner: rows pause at the relay, duplicate (key, target-host) rows merge
(weights sum), and only host-distinct rows cross the DCN hop.

All functions assume they run inside shard_map over a 1-D mesh axis.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import metrics, tracer
from ..ops import hashing, segments

SENTINEL = segments.SENTINEL


def exchange_volume_bytes(num_dev: int, capacity: int, lanes: int) -> int:
    """Global wire bytes of ONE fixed-shape collective at this site.

    Every lane is a (D, capacity) int32 buffer per device, moved whole
    regardless of how many rows are valid (that is the fixed-shape contract:
    all_to_all and all_gather volume is static).  Globally that is
    D devices x D destination rows x capacity x 4 bytes per lane.
    """
    return int(num_dev) * int(num_dev) * int(capacity) * int(lanes) * 4


def exchange_split_bytes(num_dev: int, capacity: int, lanes: int, *,
                         hosts: int = 1, hier: bool = False,
                         dcn_capacity: int | None = None,
                         reply_lanes: int = 0):
    """(ici_bytes, dcn_bytes, reply_bytes) of ONE dispatch at this site.

    Attribution follows the physical link a buffer row crosses under the
    (hosts x local) factorization: a row whose destination shares the
    sender's host rides ICI, a cross-host row rides DCN.  Flat single-hop:
    of each device's D destination rows, `local` stay on-host.  Hierarchical:
    hop 1 (full D x capacity buffer) is all ICI by construction; hop 2 moves
    `hosts` rows of `dcn_capacity` per device, hosts-1 of them cross-host.
    Reply traffic retraces the same hops, so it splits identically;
    `reply_bytes` is its (ICI + DCN) share of the totals.
    """
    d, cap = int(num_dev), int(capacity)
    hosts = max(1, int(hosts))
    local = max(1, d // hosts)
    if not hier:
        per_ici = d * local * cap * 4
        per_dcn = d * (d - local) * cap * 4
    else:
        dcn_row = int(dcn_capacity) if dcn_capacity else local * cap
        per_ici = (d * d * cap + d * dcn_row) * 4
        per_dcn = d * (hosts - 1) * dcn_row * 4
    all_lanes = int(lanes) + int(reply_lanes)
    return (per_ici * all_lanes, per_dcn * all_lanes,
            (per_ici + per_dcn) * int(reply_lanes))


def _empty_site_entry(lanes: int = 0) -> dict:
    return dict(calls=0, capacity=0, lanes=lanes, bytes=0, ici_bytes=0,
                dcn_bytes=0, reply_bytes=0, reply_lanes=0, dcn_capacity=0,
                hier=0, rows_capacity=0, rows=0, overflow_retries=0)


def log_exchange(stats, site: str, *, num_dev: int, capacity: int,
                 lanes: int, calls: int = 1, rows: int | None = None,
                 retries: int = 0, hosts: int = 1, hier: bool = False,
                 dcn_capacity: int | None = None,
                 reply_lanes: int = 0) -> None:
    """Host-side ledger of one exchange site's communication volume.

    The device collectives are fixed-shape, so the moved bytes are fully
    determined by (num_dev, capacity, lanes) x calls — the host callers that
    plan the capacities record every dispatch here (including retried and
    optimistically-discarded ones: their buffers moved too).  `rows`, when
    the host knows it, records measured valid rows; `rows_capacity` is the
    buffer-row upper bound the volume was provisioned for.  Multi-chip
    bandwidth projections divide `bytes` by the interconnect's measured
    throughput (VERDICT r5 #5).

    `hosts`/`hier`/`dcn_capacity`/`reply_lanes` drive the ICI/DCN split
    (exchange_split_bytes): `bytes` stays the grand total (forward + reply,
    both links) and always equals ici_bytes + dcn_bytes.

    Returns this dispatch's byte attribution ({site, bytes, ici, dcn,
    reply}) so a caller timing the dispatch can hand it straight to
    log_dispatch_timing.
    """
    ici1, dcn1, reply1 = exchange_split_bytes(
        num_dev, capacity, lanes, hosts=hosts, hier=hier,
        dcn_capacity=dcn_capacity, reply_lanes=reply_lanes)
    nbytes = calls * (ici1 + dcn1)
    split = {"site": site, "bytes": nbytes, "ici": calls * ici1,
             "dcn": calls * dcn1, "reply": calls * reply1}
    if stats is None:
        return split

    def fn(c):
        e = c.setdefault("exchange_sites", {}).setdefault(
            site, _empty_site_entry(lanes))
        e["calls"] += calls
        e["capacity"] = max(e["capacity"], int(capacity))
        e["lanes"] = lanes
        e["reply_lanes"] = reply_lanes
        e["bytes"] += nbytes
        e["ici_bytes"] += calls * ici1
        e["dcn_bytes"] += calls * dcn1
        e["reply_bytes"] += calls * reply1
        e["dcn_capacity"] = max(e.get("dcn_capacity", 0),
                                int(dcn_capacity or 0))
        e["hier"] = max(e.get("hier", 0), 1 if hier else 0)
        e["rows_capacity"] += calls * int(num_dev) * int(capacity)
        if rows is not None:
            e["rows"] += int(rows)
        e["overflow_retries"] += retries

    metrics.mutate(stats, fn, key="exchange_sites", kind=metrics.STRUCT)
    tracer.instant("exchange", cat=tracer.CAT_EXCHANGE, site=site,
                   calls=calls, capacity=int(capacity), bytes=nbytes,
                   dcn_bytes=calls * dcn1)
    return split


SKETCH_ALLREDUCE_SITE = "sketch_allreduce"


def sketch_allreduce_bytes(num_dev: int, bits: int, *, hosts: int = 1,
                           hier: bool = False):
    """(ici_bytes, dcn_bytes) of ONE dense (bits,) int32 table all-reduce.

    Same per-device-to-each-destination attribution as exchange_split_bytes,
    applied to a dense operand: flat, every device's table reaches d-1 peers
    (local-1 on-host rows ride ICI, d-local cross DCN).  Hierarchical, the
    intra-host psum moves the same ICI volume but each host then crosses DCN
    with ONE pre-reduced table per inter-group member — hosts-1 cross-host
    copies per device instead of d-local, a factor-`local` DCN reduction
    (the PR-8 combiner shape, with summation as the combine).
    """
    d, b = int(num_dev), int(bits) * 4
    hosts = max(1, int(hosts))
    local = max(1, d // hosts)
    ici = d * (local - 1) * b
    dcn = d * (hosts - 1) * b if hier else d * (d - local) * b
    return ici, dcn


def log_sketch_allreduce(stats, *, num_dev: int, bits: int, hosts: int = 1,
                         hier: bool = False, calls: int = 1):
    """Ledger entry for the dense count-min all-reduce site.

    Mirrors log_exchange for the sharded two-round's sketch reduction: the
    site rides the same exchange_sites struct, so the --debug exchange
    lines, the Prometheus export, and log_dispatch_timing's wall/GB/s/
    link_util attribution all cover it with zero renderer changes.
    `capacity` records the table width (counters); one int32 lane.
    Returns the split part-dict for log_dispatch_timing.
    """
    ici1, dcn1 = sketch_allreduce_bytes(num_dev, bits, hosts=hosts, hier=hier)
    nbytes = calls * (ici1 + dcn1)
    split = {"site": SKETCH_ALLREDUCE_SITE, "bytes": nbytes,
             "ici": calls * ici1, "dcn": calls * dcn1, "reply": 0}
    if stats is None:
        return split

    def fn(c):
        e = c.setdefault("exchange_sites", {}).setdefault(
            SKETCH_ALLREDUCE_SITE, _empty_site_entry(1))
        e["calls"] += calls
        e["capacity"] = max(e["capacity"], int(bits))
        e["lanes"] = 1
        e["bytes"] += nbytes
        e["ici_bytes"] += calls * ici1
        e["dcn_bytes"] += calls * dcn1
        e["hier"] = max(e.get("hier", 0), 1 if hier else 0)
        e["rows_capacity"] += calls * int(num_dev) * int(bits)
        e["rows"] += calls * int(num_dev) * int(bits)

    metrics.mutate(stats, fn, key="exchange_sites", kind=metrics.STRUCT)
    tracer.instant("exchange", cat=tracer.CAT_EXCHANGE,
                   site=SKETCH_ALLREDUCE_SITE, calls=calls,
                   capacity=int(bits), bytes=nbytes, dcn_bytes=calls * dcn1)
    return split


def sketch_allreduce(table, axis_name: str, *, cap: int, hier=None):
    """Saturating all-reduce of per-device count-min partial tables.

    Bit-identical to the host `ops.sketch.merge_count_min` over the gathered
    partials by the saturation lemma (ops.sketch.count_min_partial): the cap
    is re-applied after EVERY psum level, so each wire operand stays <= cap
    and the result equals one global sum-then-cap.

    Flat (`hier=None`): one global psum, one cap.  Hierarchical
    (`hier=(hosts, local)`): intra-host psum over the ICI groups, cap, then
    the pre-reduced per-host table psums across the DCN groups
    (hier_groups) — `local`x fewer DCN bytes than the flat reduce
    (sketch_allreduce_bytes), same bits out on every device.
    """
    if hier is None:
        return jnp.minimum(jax.lax.psum(table, axis_name), cap)
    intra, inter = hier_groups(hier)
    t = jnp.minimum(
        jax.lax.psum(table, axis_name, axis_index_groups=intra), cap)
    return jnp.minimum(
        jax.lax.psum(t, axis_name, axis_index_groups=inter), cap)


def collective_timing_enabled() -> bool:
    """Whether the per-site collective timers are armed
    (RDFIND_COLLECTIVE_TIMING=1).  Off by default: timing a dispatch means
    blocking on it (device-synchronized wall), which serializes the
    pipelined executor — measurement mode, not flight mode.  Outputs are
    bit-identical either way; only the schedule changes."""
    return os.environ.get("RDFIND_COLLECTIVE_TIMING", "") not in ("", "0")


def log_dispatch_timing(stats, parts, wall_ms: float) -> None:
    """Attribute one device-synchronized dispatch wall time across the
    exchange sites it contained.

    `parts` is the list of split dicts the dispatch's log_exchange calls
    returned (a fused device program can serve several ledger sites — e.g.
    freq + exchange_a ride one jit); the wall splits across them
    proportionally to bytes.  Per site the ledger accumulates

      wall_ms      measured wall attributed to this site,
      timed_calls / timed_bytes   how much of the site's traffic was timed,
      ideal_ms     the link-transfer lower bound of the timed traffic at the
                   probed per-hop peaks (mesh.link_probe), and derives
      gbps         achieved wire throughput (timed_bytes / wall_ms),
      link_util    ideal_ms / wall_ms — utilization-of-measured-peak; low
                   means the dispatch was compute- or latency-bound, not
                   link-bound (absent when no probe ran).

    Per-site histograms (`exchange_<site>_wall_ms`, `exchange_<site>_gbps`)
    and a trace counter track (`exchange_gbps`) ride along for p50/p95/p99
    exposition and Perfetto lanes.
    """
    parts = [p for p in parts if p]
    total = sum(p["bytes"] for p in parts)
    if not parts or total <= 0 or wall_ms <= 0:
        return
    caps = metrics.link_caps()
    ici_peak = caps.get("ici_gbps") or 0.0
    dcn_peak = caps.get("dcn_gbps") or 0.0
    for p in parts:
        share_ms = wall_ms * p["bytes"] / total
        ideal_ms = 0.0
        if ici_peak > 0:
            ideal_ms += p["ici"] / (ici_peak * 1e9) * 1e3
        if dcn_peak > 0:
            ideal_ms += p["dcn"] / (dcn_peak * 1e9) * 1e3
        gbps = p["bytes"] / (share_ms * 1e-3) / 1e9

        def fn(c, p=p, share_ms=share_ms, ideal_ms=ideal_ms):
            e = c.setdefault("exchange_sites", {}).setdefault(
                p["site"], _empty_site_entry())
            e["wall_ms"] = round(e.get("wall_ms", 0.0) + share_ms, 3)
            e["timed_calls"] = e.get("timed_calls", 0) + 1
            e["timed_bytes"] = e.get("timed_bytes", 0) + p["bytes"]
            e["ideal_ms"] = round(e.get("ideal_ms", 0.0) + ideal_ms, 3)
            wall = e["wall_ms"]
            e["gbps"] = round(e["timed_bytes"] / (wall * 1e-3) / 1e9, 3)
            if e["ideal_ms"] > 0:
                e["link_util"] = round(e["ideal_ms"] / wall, 4)

        metrics.mutate(stats, fn, key="exchange_sites", kind=metrics.STRUCT)
        metrics.observe(f"exchange_{p['site']}_wall_ms", share_ms)
        metrics.observe(f"exchange_{p['site']}_gbps", gbps)
        tracer.counter("exchange_gbps", **{p["site"]: round(gbps, 3)})


def log_exchange_retry(stats, site: str) -> None:
    """Count one overflow-retry against `site` (ledger entry created lazily
    so a retry before the first successful dispatch still lands)."""
    if stats is None:
        return

    def fn(c):
        e = c.setdefault("exchange_sites", {}).setdefault(
            site, _empty_site_entry())
        e["overflow_retries"] += 1

    metrics.mutate(stats, fn, key="exchange_sites", kind=metrics.STRUCT)
    tracer.instant("exchange_retry", cat=tracer.CAT_EXCHANGE, site=site)


def pack_counters(values):
    """Fuse scalar counters into ONE int32 lane array (device side).

    The sharded pass programs used to return overflow flags and tail counters
    as separate outputs, each costing its own blocking host_gather round trip
    per pass.  Packing every psum'd scalar into a single (K,) lane means the
    host reads ALL of a pass's control state in one (async-stageable) pull —
    the per-pass sync-count contract of the pipelined executor.
    """
    return jnp.stack([jnp.asarray(v, jnp.int32) for v in values])


def unpack_counters(host_arr, n: int, num_dev: int) -> np.ndarray:
    """Host inverse of pack_counters over a P(AXIS)-gathered output.

    Every lane is globally reduced (psum/pmax) on device, so all devices
    carry identical copies; device 0's row is the answer.
    """
    return np.asarray(host_arr).reshape(num_dev, n)[0]


def hier_groups(hier):
    """(intra, inter) axis_index_groups for a (hosts, local) factorization.

    Device d = h * local + l.  `intra` groups the devices of one host (the
    ICI hop); `inter` groups same-local-index devices across hosts (the DCN
    hop).  Both partitions cover the axis, as all_to_all requires.
    """
    h, l = hier
    intra = [[hh * l + ll for ll in range(l)] for hh in range(h)]
    inter = [[hh * l + ll for hh in range(h)] for ll in range(l)]
    return intra, inter


def _a2a(buf, axis_name: str, groups=None, chunks: int = 1):
    """Tiled row all_to_all, optionally as `chunks` independent collectives
    over slices of the capacity axis (each slice is slot-preserving on its
    own, so concatenation is bit-identical to the unchunked op — the chunks
    exist to give the dispatch-ahead executor overlappable DCN pieces)."""
    if chunks > 1 and buf.shape[1] % chunks == 0:
        return jnp.concatenate(
            [jax.lax.all_to_all(p, axis_name, split_axis=0, concat_axis=0,
                                tiled=True, axis_index_groups=groups)
             for p in jnp.split(buf, chunks, axis=1)], axis=1)
    return jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                              tiled=True, axis_index_groups=groups)


def _hier_fwd(buf, hier, axis_name: str, dcn_chunks: int = 1):
    """Two-hop forward exchange of a hier-slotted (D*capacity,) send buffer.

    The sender lays rows out [l_t, h_t, k] (local index of the target first).
    Hop 1 (ICI): each host's devices all_to_all (L, H*cap) rows, so the relay
    device (h_s, l_t) collects every local source's block destined for local
    index l_t — laid out [l_s, h_t, k].  A transpose regroups by target host
    and hop 2 (DCN) all_to_alls (H, L*cap) across hosts, landing [h_s, l_s, k]
    on the target — which IS the flat path's (src, k) receive layout, so
    downstream consumers cannot tell the difference.
    """
    h, l = hier
    intra, inter = hier_groups(hier)
    cap = buf.shape[0] // (h * l)
    r = jax.lax.all_to_all(buf.reshape(l, h * cap), axis_name, split_axis=0,
                           concat_axis=0, tiled=True, axis_index_groups=intra)
    r = r.reshape(l, h, cap).transpose(1, 0, 2).reshape(h, l * cap)
    return _a2a(r, axis_name, groups=inter, chunks=dcn_chunks).reshape(-1)


def _hier_back(answer, hier, axis_name: str, dcn_chunks: int = 1):
    """Reverse both hops: a (D*capacity,) [src, k]-layout answer retraces DCN
    then ICI back into the sender's [l_t, h_t, k] send-slot layout."""
    h, l = hier
    intra, inter = hier_groups(hier)
    cap = answer.shape[0] // (h * l)
    r = _a2a(answer.reshape(h, l * cap), axis_name, groups=inter,
             chunks=dcn_chunks)
    r = r.reshape(h, l, cap).transpose(1, 0, 2).reshape(l, h * cap)
    return jax.lax.all_to_all(r, axis_name, split_axis=0, concat_axis=0,
                              tiled=True, axis_index_groups=intra).reshape(-1)


@dataclasses.dataclass
class RouteState:
    """Slot mapping of one routed exchange (everything route_reply needs)."""

    perm: jnp.ndarray  # sorted order -> original row index
    flat: jnp.ndarray  # per sorted row: slot in the (D*capacity) send buffer
    ok: jnp.ndarray    # per sorted row: survived (valid and under capacity)
    num_dev: int
    capacity: int
    hier: tuple | None = None  # (hosts, local) of the two-hop path, if taken
    dcn_chunks: int = 1


def route(cols, valid, bucket, axis_name: str, capacity: int, *,
          hier=None, dcn_chunks: int = 1):
    """Route rows to the device equal to their bucket id.

    cols     -- list of (N,) int32 columns (row payload; SENTINEL is reserved);
    valid    -- (N,) bool;
    bucket   -- (N,) int32 destination device in [0, D);
    capacity -- static per-destination row budget;
    hier     -- optional (hosts, local) factorization: run the shuffle as an
                intra-host hop then an inter-host hop (see _hier_fwd).  The
                receive layout, validity, and overflow are bit-identical to
                the flat path (same per-destination slotting math).

    Returns (out_cols, out_valid, overflow, state): out_cols are (D*capacity,)
    columns of rows received by this device (garbage where ~out_valid); overflow
    is the global number of rows dropped for exceeding a bucket capacity; state
    feeds route_reply for sending per-received-row answers back.
    """
    d = jax.lax.psum(1, axis_name)
    n = cols[0].shape[0]
    tgt = jnp.where(valid, bucket, d)  # invalid rows to a virtual overflow bucket
    perm = segments.lexsort([tgt])
    t_s = tgt[perm]
    v_s = valid[perm]
    # Position of each row within its destination group.
    idx = jnp.arange(n, dtype=jnp.int32)
    starts = segments.run_starts([t_s])
    run_start = jax.lax.cummax(jnp.where(starts, idx, 0))
    pos = idx - run_start
    ok = v_s & (pos < capacity)
    if hier is None:
        slot_dev = t_s
    else:
        # Hier send layout [l_t, h_t, k]: same (destination, pos) slots, just
        # permuted at block granularity — ok/pos/overflow stay flat-identical.
        hh, ll = hier
        slot_dev = (t_s % ll) * hh + (t_s // ll)
    flat = jnp.where(ok, slot_dev * capacity + pos, d * capacity)  # OOB => drop
    overflow_local = (v_s & ~ok).sum()
    overflow = jax.lax.psum(overflow_local, axis_name)

    def xchg(buf):
        if hier is None:
            return jax.lax.all_to_all(buf.reshape(d, capacity), axis_name,
                                      split_axis=0, concat_axis=0,
                                      tiled=True).reshape(-1)
        return _hier_fwd(buf, hier, axis_name, dcn_chunks=dcn_chunks)

    out_cols = []
    for c in cols:
        buf = jnp.full(d * capacity, SENTINEL, jnp.int32)
        buf = buf.at[flat].set(c[perm], mode="drop")
        out_cols.append(xchg(buf))

    # Validity travels as its own lane so payload SENTINELs stay representable.
    # NB: `ok` is already in sorted order (aligned with `flat`), unlike the
    # payload columns above which are in original order — indexing it with
    # `perm` again would sample validity from unrelated rows and silently drop
    # rows whenever the valid mask is not a compacted prefix.
    vbuf = jnp.zeros(d * capacity, jnp.int32).at[flat].set(
        ok.astype(jnp.int32), mode="drop")
    recv_v = xchg(vbuf)
    state = RouteState(perm=perm, flat=flat, ok=ok, num_dev=d,
                       capacity=capacity, hier=hier, dcn_chunks=dcn_chunks)
    return out_cols, recv_v == 1, overflow, state


def route_reply(answer, state: RouteState, axis_name: str):
    """Send one (D*capacity,) int32 answer-per-received-row back to the senders.

    Returns an (N,) column in the *original row order* of the route() call; rows
    that were dropped (overflow) or invalid get 0.  A hierarchical route's
    reply retraces both hops in reverse (DCN then ICI) into the same send
    slots, so the caller-visible contract is unchanged.
    """
    n = state.perm.shape[0]
    if state.hier is None:
        back = jax.lax.all_to_all(
            answer.reshape(state.num_dev, state.capacity), axis_name,
            split_axis=0, concat_axis=0, tiled=True).reshape(-1)
    else:
        back = _hier_back(answer, state.hier, axis_name,
                          dcn_chunks=state.dcn_chunks)
    safe = jnp.clip(state.flat, 0, state.num_dev * state.capacity - 1)
    vals = jnp.where(state.ok, back[safe], 0)
    return jnp.zeros(n, jnp.int32).at[state.perm].set(vals)


def bucket_exchange(cols, valid, bucket, axis_name: str, capacity: int, *,
                    hier=None, dcn_chunks: int = 1):
    """route() without the reply half (the one-way shuffle)."""
    out_cols, out_valid, overflow, _ = route(cols, valid, bucket, axis_name,
                                             capacity, hier=hier,
                                             dcn_chunks=dcn_chunks)
    return out_cols, out_valid, overflow


@dataclasses.dataclass
class CombinedState:
    """Slot + combine mappings of one route_combined (for the reply path)."""

    perm: jnp.ndarray   # hop 1: sorted order -> original row index
    flat: jnp.ndarray   # hop 1: per sorted row, hier send-buffer slot
    ok: jnp.ndarray     # hop 1: per sorted row, survived
    uinv: jnp.ndarray   # relay: row -> its combined unique row
    rvalid: jnp.ndarray  # relay: received-row validity
    perm2: jnp.ndarray  # hop 2: sorted order -> unique row index
    flat2: jnp.ndarray  # hop 2: per sorted unique row, DCN send-buffer slot
    ok2: jnp.ndarray    # hop 2: per sorted unique row, survived
    num_dev: int
    capacity: int
    dcn_capacity: int
    hier: tuple
    dcn_chunks: int = 1


def route_combined(cols, weight, valid, bucket, axis_name: str,
                   capacity: int, dcn_capacity: int, hier, *,
                   dcn_chunks: int = 1):
    """Two-level route with per-host pre-aggregation before the DCN hop (the
    Flink combiner-before-shuffle analog).

    Rows ride the ICI hop exactly as route(hier=...) — same slotting math,
    so `overflow` is bit-identical to the flat path's count — but pause at
    the intra-host relay, where duplicate (key columns, target host) rows
    merge into one: `weight` sum-combines (pass ones for multiplicities;
    None skips the weight lane entirely — pure dedupe, out_weight is None).
    Only the host-distinct survivors cross the DCN hop, into a separate
    (hosts, dcn_capacity) budget.

    REQUIRES `bucket` to be a pure function of `cols`: rows that compare
    equal on the key columns must share a destination, or merging them would
    change routing semantics.  Every combined call site hashes the key
    columns (rebalance's data-driven destinations use the slot-preserving
    route instead).

    Returns (out_cols, out_weight, out_valid, (overflow, overflow_dcn),
    state): out_* are (hosts*dcn_capacity,) combined rows received by the
    owner — the same key may still arrive once per source HOST, so owners
    keep their masked_unique/segment-sum merge; summed integer weights make
    downstream totals bit-identical to the flat path.  `state` feeds
    route_combined_reply.
    """
    hh, ll = hier
    d = jax.lax.psum(1, axis_name)
    n = cols[0].shape[0]
    intra, inter = hier_groups(hier)

    # Hop 1 (ICI): route()'s slotting math verbatim, hier send layout.
    tgt = jnp.where(valid, bucket, d)
    perm = segments.lexsort([tgt])
    t_s = tgt[perm]
    v_s = valid[perm]
    idx = jnp.arange(n, dtype=jnp.int32)
    starts = segments.run_starts([t_s])
    run_start = jax.lax.cummax(jnp.where(starts, idx, 0))
    pos = idx - run_start
    ok = v_s & (pos < capacity)
    slot_dev = (t_s % ll) * hh + (t_s // ll)
    flat = jnp.where(ok, slot_dev * capacity + pos, d * capacity)
    overflow = jax.lax.psum((v_s & ~ok).sum(), axis_name)

    def hop1(c, fill):
        buf = jnp.full(d * capacity, fill, jnp.int32).at[flat].set(
            c, mode="drop").reshape(ll, hh * capacity)
        return jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                                  tiled=True,
                                  axis_index_groups=intra).reshape(-1)

    r_cols = [hop1(c[perm], SENTINEL) for c in cols]
    r_w = hop1(jnp.where(ok, weight[perm], 0), 0) if weight is not None \
        else None
    rvalid = hop1(ok.astype(jnp.int32), 0) == 1

    # Relay combine: rows sit at [l_s, h_t, k], so the target host of slot i
    # is structural — no re-hash needed.  Merge per (key, target host).
    m = d * capacity
    r_ht = (jnp.arange(m, dtype=jnp.int32) // capacity) % hh
    u_cols, u_valid, uinv, _ = segments.masked_unique(r_cols + [r_ht], rvalid)
    uinv_safe = jnp.clip(uinv, 0, m - 1)
    u_w = (jax.ops.segment_sum(jnp.where(rvalid, r_w, 0), uinv_safe,
                               num_segments=m)
           if r_w is not None else None)
    u_ht = u_cols[-1]

    # Hop 2 (DCN): slot the combined rows against the per-host budget.
    tgt2 = jnp.where(u_valid, u_ht, hh)
    perm2 = segments.lexsort([tgt2])
    t2_s = tgt2[perm2]
    v2_s = u_valid[perm2]
    idx2 = jnp.arange(m, dtype=jnp.int32)
    starts2 = segments.run_starts([t2_s])
    rs2 = jax.lax.cummax(jnp.where(starts2, idx2, 0))
    pos2 = idx2 - rs2
    ok2 = v2_s & (pos2 < dcn_capacity)
    flat2 = jnp.where(ok2, t2_s * dcn_capacity + pos2, hh * dcn_capacity)
    overflow_dcn = jax.lax.psum((v2_s & ~ok2).sum(), axis_name)

    def hop2(c, fill):
        buf = jnp.full(hh * dcn_capacity, fill, jnp.int32).at[flat2].set(
            c, mode="drop").reshape(hh, dcn_capacity)
        return _a2a(buf, axis_name, groups=inter,
                    chunks=dcn_chunks).reshape(-1)

    out_cols = [hop2(c[perm2], SENTINEL) for c in u_cols[:-1]]
    out_w = hop2(u_w[perm2], 0) if u_w is not None else None
    out_valid = hop2(ok2.astype(jnp.int32), 0) == 1
    state = CombinedState(perm=perm, flat=flat, ok=ok, uinv=uinv,
                          rvalid=rvalid, perm2=perm2, flat2=flat2, ok2=ok2,
                          num_dev=d, capacity=capacity,
                          dcn_capacity=dcn_capacity, hier=hier,
                          dcn_chunks=dcn_chunks)
    return out_cols, out_w, out_valid, (overflow, overflow_dcn), state


def route_combined_reply(answer, state: CombinedState, axis_name: str):
    """Per-received-combined-row answers back to the ORIGINAL senders' rows.

    Reverses the DCN hop to the relay, fans each combined row's answer out to
    every relay row that merged into it, then reverses the ICI hop.  Returns
    an (N,) column in route_combined()'s original row order (0 where the row
    was invalid or dropped at either hop).
    """
    hh, ll = state.hier
    cap, dcap = state.capacity, state.dcn_capacity
    intra, inter = hier_groups(state.hier)
    m = state.perm2.shape[0]
    back2 = _a2a(answer.reshape(hh, dcap), axis_name, groups=inter,
                 chunks=state.dcn_chunks).reshape(-1)
    safe2 = jnp.clip(state.flat2, 0, hh * dcap - 1)
    vals2 = jnp.where(state.ok2, back2[safe2], 0)
    ans_comb = jnp.zeros(m, jnp.int32).at[state.perm2].set(vals2)
    # Fan out: every relay row inherits its combined representative's answer.
    uinv_safe = jnp.clip(state.uinv, 0, m - 1)
    ans_relay = jnp.where(state.rvalid, ans_comb[uinv_safe], 0)
    back1 = jax.lax.all_to_all(
        ans_relay.reshape(ll, hh * cap), axis_name, split_axis=0,
        concat_axis=0, tiled=True, axis_index_groups=intra).reshape(-1)
    n = state.perm.shape[0]
    safe1 = jnp.clip(state.flat, 0, state.num_dev * cap - 1)
    vals = jnp.where(state.ok, back1[safe1], 0)
    return jnp.zeros(n, jnp.int32).at[state.perm].set(vals)


def global_row_counts(key_cols, valid, axis_name: str, capacity: int, *,
                      seed: int, hier=None, dcn_capacity: int | None = None,
                      dcn_chunks: int = 1):
    """Per-row GLOBAL count of the row's key across all devices.

    Combiner-tree + join-back in one primitive: local distinct keys carry their
    local multiplicities to the key's hash owner (one all_to_all of *distinct*
    keys, not rows), the owner sums them, and the sums ride the reply collective
    back to every asking row.  Exchange volume is O(local distinct keys).

    Hierarchical mode (`hier` + `dcn_capacity`) lifts the combiner a level:
    per-DEVICE distinct keys merge into per-HOST distinct keys at the relay
    (local multiplicities sum there), and only those cross DCN.  Integer sums
    are order-free, so the returned counts are bit-identical; hop-1 overflow
    matches flat bit-for-bit and DCN-budget overflow folds into the same
    returned counter (either way the caller's contract is "retry bigger").

    Returns (counts, overflow): counts is (N,) int32, 0 for invalid rows;
    overflow > 0 means a capacity was too small and counts are unusable.
    """
    d = jax.lax.psum(1, axis_name)
    u_cols, u_valid, inv, _ = segments.masked_unique(key_cols, valid)
    m = u_cols[0].shape[0]
    inv_safe = jnp.clip(inv, 0, m - 1)
    local_mult = jax.ops.segment_sum(valid.astype(jnp.int32), inv_safe,
                                     num_segments=m)
    bucket = hashing.bucket_of(u_cols, d, seed=seed)
    if hier is None:
        recv, recv_valid, overflow, state = route(
            u_cols + [local_mult], u_valid, bucket, axis_name, capacity)
        g = segments.masked_weighted_row_counts(recv[:-1], recv[-1],
                                                recv_valid)
        ans_per_distinct = route_reply(g, state, axis_name)
        return jnp.where(valid, ans_per_distinct[inv_safe], 0), overflow
    recv, recv_w, recv_valid, (ovf, ovf_dcn), state = route_combined(
        u_cols, local_mult, u_valid, bucket, axis_name, capacity,
        dcn_capacity, hier, dcn_chunks=dcn_chunks)
    g = segments.masked_weighted_row_counts(recv, recv_w, recv_valid)
    ans_per_distinct = route_combined_reply(g, state, axis_name)
    return jnp.where(valid, ans_per_distinct[inv_safe], 0), ovf + ovf_dcn


def global_distinct_frequent(key_cols, valid, min_support, axis_name: str,
                             capacity: int, *, seed: int, hier=None,
                             dcn_capacity: int | None = None,
                             dcn_chunks: int = 1):
    """GLOBAL number of distinct keys occurring >= min_support times.

    The distributed form of the --find-only-fcs report (the reference counts
    its frequent-condition Bloom filters cluster-wide): local distinct keys
    carry local multiplicities to their hash owner, the owner sums and counts
    its frequent keys, and a psum totals the owners.  Returns (count,
    overflow); overflow > 0 invalidates the count (grow `capacity`).
    """
    d = jax.lax.psum(1, axis_name)
    u_cols, u_valid, inv, _ = segments.masked_unique(key_cols, valid)
    m = u_cols[0].shape[0]
    inv_safe = jnp.clip(inv, 0, m - 1)
    local_mult = jax.ops.segment_sum(valid.astype(jnp.int32), inv_safe,
                                     num_segments=m)
    bucket = hashing.bucket_of(u_cols, d, seed=seed)
    if hier is None:
        recv, recv_valid, overflow, _ = route(u_cols + [local_mult], u_valid,
                                              bucket, axis_name, capacity)
        g = segments.masked_weighted_row_counts(recv[:-1], recv[-1],
                                                recv_valid)
        ok = recv_valid & (g >= min_support)
        _, _, _, n_u = segments.masked_unique(recv[:-1], ok)
        return jax.lax.psum(n_u, axis_name), overflow
    recv, recv_w, recv_valid, (ovf, ovf_dcn), _ = route_combined(
        u_cols, local_mult, u_valid, bucket, axis_name, capacity,
        dcn_capacity, hier, dcn_chunks=dcn_chunks)
    # The owner still dedupes: the same key arrives once per source HOST.
    g = segments.masked_weighted_row_counts(recv, recv_w, recv_valid)
    ok = recv_valid & (g >= min_support)
    _, _, _, n_u = segments.masked_unique(recv, ok)
    return jax.lax.psum(n_u, axis_name), ovf + ovf_dcn


def sorted_join_counts(table_cols, table_counts, table_valid, query_cols, query_valid):
    """For each query row, the count of its key in a distinct-key table (0 if absent).

    Both sides are lists of int32 key columns of fixed shapes.  Implemented as a
    tag-sorted merge join: concatenate [table rows (tag 0), query rows (tag 1)],
    lexsort by (key..., tag); each run starts with the table row (if present), whose
    count forward-fills to the run's query rows.
    """
    nt = table_cols[0].shape[0]
    nq = query_cols[0].shape[0]
    tag = jnp.concatenate([jnp.zeros(nt, jnp.int32), jnp.ones(nq, jnp.int32)])
    allv = jnp.concatenate([table_valid, query_valid])
    keys = [
        jnp.where(allv, jnp.concatenate([t, q]), SENTINEL)
        for t, q in zip(table_cols, query_cols)
    ]
    cnt = jnp.concatenate([table_counts, jnp.zeros(nq, jnp.int32)])

    perm = segments.lexsort(keys + [tag])
    keys_s = [k[perm] for k in keys]
    tag_s = tag[perm]
    cnt_s = cnt[perm]
    idx = jnp.arange(nt + nq, dtype=jnp.int32)
    starts = segments.run_starts(keys_s)
    run_start = jax.lax.cummax(jnp.where(starts, idx, 0))
    cnt_at_start = cnt_s[run_start]
    tag_at_start = tag_s[run_start]
    filled = jnp.where(tag_at_start == 0, cnt_at_start, 0)

    # Scatter back to query order: positions of query rows in the concat array.
    out = jnp.zeros(nt + nq, jnp.int32).at[perm].set(filled)
    return out[nt:]
