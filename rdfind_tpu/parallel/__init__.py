"""Collective dataflow layer: mesh construction + bucket exchanges over ICI/DCN.

The TPU-native replacement for the reference's Flink shuffle runtime (hash shuffles
between operators, broadcast variables, combiner trees — SURVEY.md §2h): a shuffle is
a fixed-capacity bucket exchange built on jax.lax.all_to_all inside shard_map, a
broadcast is replication/psum, and the driver↔worker control plane is the host
program orchestrating jitted collective steps.
"""
