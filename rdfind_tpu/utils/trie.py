"""Path-squashed string trie for longest-prefix matching.

Semantics follow the reference's StringTrie (util/StringTrie.scala:8-118, tested by
StringTrie$Test.scala): insert key/value pairs, optionally squash chains, and look up
the value of the longest key that prefixes a query string.
"""

from __future__ import annotations


class _Node:
    __slots__ = ("edge", "children", "value", "has_value")

    def __init__(self, edge: str = ""):
        self.edge = edge  # squashed edge label leading INTO this node
        self.children: dict[str, _Node] = {}
        self.value = None
        self.has_value = False


class StringTrie:
    """Trie over strings; `longest_prefix_value(q)` finds the value of the longest
    inserted key that is a prefix of q (None if no key matches)."""

    def __init__(self):
        self._root = _Node()

    def __setitem__(self, key: str, value) -> None:
        node = self._root
        for ch in key:
            node = node.children.setdefault(ch, _Node(ch))
        node.value = value
        node.has_value = True

    def squash(self) -> None:
        """Collapse single-child, valueless chains (the reference's squash());
        lookups work identically before and after."""

        def squash_node(node: _Node) -> None:
            for key, child in list(node.children.items()):
                while len(child.children) == 1 and not child.has_value:
                    (only,) = child.children.values()
                    only.edge = child.edge + only.edge
                    child = only
                node.children[key] = child
                squash_node(child)

        squash_node(self._root)

    def longest_prefix_value(self, query: str):
        node = self._root
        best = self._root.value if self._root.has_value else None
        i = 0
        n = len(query)
        while i < n:
            child = node.children.get(query[i])
            if child is None:
                break
            edge = child.edge
            if len(edge) > 1:
                if not query.startswith(edge, i):
                    break
                i += len(edge)
            else:
                i += 1
            node = child
            if node.has_value:
                best = node.value
        return best
