"""Host-side helpers: synthetic data generation, sorted-set algebra, tries."""
