"""Synthetic RDF workload generation for benchmarks and tests.

Shapes the data like the reference's target datasets (LUBM / DBpedia, BASELINE.md):
a few predicates with zipf-ish popularity, subject/object pools with heavy reuse so
join lines have a realistic power-law size distribution (incl. hub values), and
enough value overlap across predicates that real CINDs exist.
"""

from __future__ import annotations

import numpy as np


def generate_triples(n: int, seed: int = 0, n_predicates: int = 24,
                     n_entities: int | None = None) -> np.ndarray:
    """(n, 3) int32 id triples.  Ids are disjoint across fields except that objects
    reuse the subject pool with probability ~0.3 (URI objects), creating cross-field
    join lines like real RDF."""
    rng = np.random.default_rng(seed)
    if n_entities is None:
        n_entities = max(16, n // 8)
    n_literals = max(16, n // 4)

    # Zipf-ish predicate popularity.
    ranks = np.arange(1, n_predicates + 1, dtype=np.float64)
    p_pred = (1.0 / ranks) / (1.0 / ranks).sum()
    pred = rng.choice(n_predicates, size=n, p=p_pred).astype(np.int32)

    # Subjects: zipf-ish entity reuse.
    subj = (rng.zipf(1.3, size=n) % n_entities).astype(np.int32)

    # Objects: 30% entity pool (URIs), 70% literal pool; literals skewed so a few
    # hub values produce giant join lines.
    is_uri = rng.random(n) < 0.3
    obj_uri = (rng.zipf(1.3, size=n) % n_entities).astype(np.int32)
    obj_lit = (rng.zipf(1.5, size=n) % n_literals).astype(np.int32)

    # Field-disjoint id spaces (except subj/obj URI sharing).
    subj_ids = subj
    pred_ids = n_entities + pred
    obj_ids = np.where(is_uri, obj_uri, n_entities + n_predicates + obj_lit)
    return np.stack([subj_ids, pred_ids, obj_ids.astype(np.int32)], axis=1)
