"""Synthetic RDF workload generation for benchmarks and tests.

Shapes the data like the reference's target datasets (LUBM / DBpedia, BASELINE.md):
a few predicates with zipf-ish popularity, subject/object pools with heavy reuse so
join lines have a realistic power-law size distribution (incl. hub values), and
enough value overlap across predicates that real CINDs exist.
"""

from __future__ import annotations

import numpy as np


def generate_triples(n: int, seed: int = 0, n_predicates: int = 24,
                     n_entities: int | None = None) -> np.ndarray:
    """(n, 3) int32 id triples.  Ids are disjoint across fields except that objects
    reuse the subject pool with probability ~0.3 (URI objects), creating cross-field
    join lines like real RDF."""
    rng = np.random.default_rng(seed)
    if n_entities is None:
        n_entities = max(16, n // 8)
    n_literals = max(16, n // 4)

    # Zipf-ish predicate popularity.
    ranks = np.arange(1, n_predicates + 1, dtype=np.float64)
    p_pred = (1.0 / ranks) / (1.0 / ranks).sum()
    pred = rng.choice(n_predicates, size=n, p=p_pred).astype(np.int32)

    # Subjects: zipf-ish entity reuse.
    subj = (rng.zipf(1.3, size=n) % n_entities).astype(np.int32)

    # Objects: 30% entity pool (URIs), 70% literal pool; literals skewed so a few
    # hub values produce giant join lines.
    is_uri = rng.random(n) < 0.3
    obj_uri = (rng.zipf(1.3, size=n) % n_entities).astype(np.int32)
    obj_lit = (rng.zipf(1.5, size=n) % n_literals).astype(np.int32)

    # Field-disjoint id spaces (except subj/obj URI sharing).
    subj_ids = subj
    pred_ids = n_entities + pred
    obj_ids = np.where(is_uri, obj_uri, n_entities + n_predicates + obj_lit)
    return np.stack([subj_ids, pred_ids, obj_ids.astype(np.int32)], axis=1)


def inject_cind_structure(triples: np.ndarray, n_rules: int = 32,
                          ref_size: int = 150,
                          dep_size: int = 120) -> np.ndarray:
    """Append a structured overlay that plants genuine high-support CINDs.

    IID-ish synthetic data cannot sustain *exact* containment at high support
    (more triples per capture means more distinct values, so perfect inclusion
    gets rarer as n grows) — real RDF has structural inclusions instead
    (every <x a :Professor> also <x a :Person>).  This overlay reproduces
    that: for each of ``n_rules`` fresh predicate pairs (a, b), ``ref_size``
    fresh subjects get (s, b, o_s) and the first ``dep_size`` of them also get
    (s, a, o'_s), making s[p=a] < s[p=b] hold exactly with support
    ``dep_size``.  Fresh id ranges keep the overlay from perturbing the base
    distribution.
    """
    base = int(triples.max()) + 1 if triples.size else 0
    rows = []
    for k in range(n_rules):
        subj = base + np.arange(ref_size, dtype=np.int64)
        pred_a = base + ref_size + 2 * k
        pred_b = pred_a + 1
        obj_b = base + ref_size + 2 * n_rules + np.arange(ref_size)
        obj_a = obj_b + ref_size  # distinct object pool per side
        if k % 2 == 0:
            rows.append(np.stack([subj, np.full(ref_size, pred_b), obj_b], 1))
        else:
            # Shared object on the referenced side: the tightest referenced
            # capture is the *binary* s[p=b, o=hub], planting 1/2-family
            # CINDs as well.
            hub = obj_b[0]
            rows.append(np.stack([subj, np.full(ref_size, pred_b),
                                  np.full(ref_size, hub)], 1))
        rows.append(np.stack([subj[:dep_size], np.full(dep_size, pred_a),
                              obj_a[:dep_size]], 1))
        base = int(max(obj_a.max(), pred_b)) + 1
    overlay = np.concatenate(rows).astype(np.int32)
    return np.concatenate([np.asarray(triples, np.int32), overlay])


def generate_planted_cinds(n_rules: int, support: int, *,
                           ref_size: int | None = None,
                           base_triples: np.ndarray | None = None,
                           seed: int = 0):
    """CIND-dense planted workload: ``n_rules`` MINIMAL CINDs per family.

    The scale proxies' weakness (VERDICT r5 #4): at support >= 1000 the
    zipf-shaped generators emit 5-276 CINDs, so minimality cleanup, family
    split, decode, and sinks run at toy volume while the pair phase runs at
    scale.  This generator plants inclusion structure whose CIND count
    scales with ``n_rules``: one rule per family per k, each surviving the
    implied-CIND cleanup (so the counts hold for raw AllAtOnce output AND
    for the minimal set every strategy converges to under clean_implied) —
    ``n_rules = 2500`` at ``support = 1000`` yields >= 10^4 minimal CINDs
    across all four families.

    Per rule k (fresh id ranges, so rules never interact), with S_dep the
    first ``support`` of ``ref_size`` fresh referenced subjects:

    * family 1/1: dep (s, pa, o_s) / ref (s, pb, o'_s), per-row distinct
      objects — binary captures stay infrequent, so s[pa] < s[pb] is the
      only (and minimal) planted CIND;
    * family 1/2: ref rows share object hub_b, so the minimal form is
      s[pa] < s[pb, o=hub_b] (the implied 1/1 against s[pb] is cleaned);
    * family 2/1: dep rows share object hub_a AND ``spoiler`` extra dep
      subjects outside the ref break the unary inclusion, so
      s[pa, o=hub_a] < s[pb] is minimal (no implying 1/1 exists);
    * family 2/2: both hubs plus spoilers: s[pa, o=hub_a] < s[pb, o=hub_b]
      minimal.

    Returns (triples, expected): ``expected`` maps family -> planted count,
    a LOWER bound on table.family_counts() (hub/unary ref captures of equal
    extent add a few benign same-rule CINDs on top).

    ``base_triples`` prepends a background workload (e.g. generate_triples)
    in its own id range, for realism without perturbing the planted counts.
    """
    if ref_size is None:
        ref_size = support + max(support // 4, 8)
    if ref_size <= support:
        raise ValueError("ref_size must exceed support (strict inclusion)")
    n_spoil = max(2, support // 8)
    rows = []
    base = 0
    if base_triples is not None and base_triples.size:
        rows.append(np.asarray(base_triples, np.int32))
        base = int(base_triples.max()) + 1
    del seed  # deterministic by construction; kept for API symmetry

    def fresh(n):
        nonlocal base
        out = base + np.arange(n, dtype=np.int64)
        base += n
        return out

    for _ in range(n_rules):
        for dep_binary, ref_binary in ((False, False), (False, True),
                                       (True, False), (True, True)):
            subj = fresh(ref_size)
            pa, pb = fresh(1)[0], fresh(1)[0]
            # Referenced side: hub object (binary ref capture frequent and
            # equal-extent with the unary) or per-row distinct objects
            # (binary ref captures infrequent).
            obj_b = (np.full(ref_size, fresh(1)[0]) if ref_binary
                     else fresh(ref_size))
            rows.append(np.stack([subj, np.full(ref_size, pb), obj_b], 1))
            # Dependent side over the first `support` referenced subjects.
            obj_a = (np.full(support, fresh(1)[0]) if dep_binary
                     else fresh(support))
            rows.append(np.stack([subj[:support], np.full(support, pa),
                                  obj_a], 1))
            if dep_binary:
                # Spoilers: the binary dep (pa, o=hub_a) must not be implied
                # by EITHER of its unary parents, so both get broken on
                # subjects outside the ref: pa rows with distinct non-hub
                # objects (s[pa] not included) and hub_a rows under a fresh
                # predicate (s[o=hub_a] not included).  The binary capture
                # itself stays exactly the dependent subjects.
                rows.append(np.stack([fresh(n_spoil),
                                      np.full(n_spoil, pa),
                                      fresh(n_spoil)], 1))
                rows.append(np.stack([fresh(n_spoil),
                                      np.full(n_spoil, fresh(1)[0]),
                                      np.full(n_spoil, obj_a[0])], 1))
    if base >= np.iinfo(np.int32).max:
        raise ValueError("planted workload exceeds int32 id space")
    triples = np.concatenate(rows).astype(np.int32) if rows else \
        np.zeros((0, 3), np.int32)
    expected = {f: n_rules for f in ("11", "12", "21", "22")}
    return triples, expected


def generate_dbpedia_shaped(n: int, seed: int = 0) -> np.ndarray:
    """(n, 3) int32 triples with DBpedia-like cardinalities for SCALE runs.

    What the quadratic pair phase squares over is the number of FREQUENT
    captures per join line, so the generator controls per-id degrees
    directly: zipf draws are split into degree-capped clones (subjects and
    URI objects cap at ~64 rows per generation call, literals at ~128 —
    below a support-1000 threshold, like the long tail of real DBpedia),
    plus ~200 enumeration-style hub literals (country/type names) whose
    degree is ~n * 1.5e-4: they clear support 1000 once n >= ~7M and stay
    infrequent below that (at the measured 2M scale point object conditions
    are all infrequent, so its CINDs are predicate-level).  Predicates
    follow a 1.2-exponent
    zipf over ~1.2k ids with a true rdf:type-like hub.  Total line-pair
    volume then scales like n * cap — the reference's target regime — not
    with the hottest id.
    """
    rng = np.random.default_rng(seed)
    n_subj = max(64, n // 12)
    n_pred = 1200
    n_lit = max(64, n // 6)
    n_hub_lit = 200

    def capped_zipf(a, size, n_vals, cap):
        """Zipf-shaped draws with per-id degree capped at ~cap rows.

        The per-call random base keeps independently-seeded chunks from
        stacking degrees on the same ids (rank 1 clone 0 must not map to one
        global id across every chunk of a chunked generation — that would
        grow hub degree as cap x n_chunks and void the cap).
        """
        v = rng.zipf(a, size=size).astype(np.int64)
        order = np.argsort(v, kind="stable")
        vs = v[order]
        run_start = np.flatnonzero(np.r_[True, vs[1:] != vs[:-1]])
        run_len = np.diff(np.append(run_start, len(vs)))
        within = np.arange(len(vs)) - np.repeat(run_start, run_len)
        clone = within // cap
        base = rng.integers(0, n_vals)
        ids = (vs * 1000003 + clone * 7919 + base) % n_vals
        out = np.empty(size, np.int64)
        out[order] = ids
        return out.astype(np.int32)

    subj = capped_zipf(1.7, n, n_subj, 64)
    ranks = np.arange(1, n_pred + 1, dtype=np.float64)
    p_pred = (1.0 / ranks ** 1.2)
    p_pred /= p_pred.sum()
    pred = rng.choice(n_pred, size=n, p=p_pred).astype(np.int32)
    is_uri = rng.random(n) < 0.4
    obj_uri = capped_zipf(1.7, n, n_subj, 64)
    obj_lit = capped_zipf(2.1, n, n_lit, 128)
    # Enumeration-style hub literals: uncapped, genuinely frequent objects.
    is_hub = (~is_uri) & (rng.random(n) < 0.05)
    obj_lit = np.where(is_hub, n_lit + rng.integers(0, n_hub_lit, n),
                       obj_lit).astype(np.int32)

    subj_ids = subj
    pred_ids = n_subj + pred
    obj_ids = np.where(is_uri, obj_uri,
                       n_subj + n_pred + obj_lit)
    return np.stack([subj_ids, pred_ids, obj_ids.astype(np.int32)], axis=1)


def triples_to_tokens(triples: np.ndarray) -> list[tuple[str, str, str]]:
    """Integer id triples -> the `<v%09d>` URI tokens the .nt writers emit.

    Zero-padded so lexicographic token order == numeric id order: the
    canonical (sorted) dictionary a from-scratch run interns then ranks the
    tokens exactly like the generator ranked the ids, which keeps planted
    workloads easy to reason about in delta tests."""
    return [tuple(f"<v{int(v):09d}>" for v in row)
            for row in np.asarray(triples).reshape(-1, 3)]


def write_nt(path, triples: np.ndarray) -> None:
    """Serialize integer id triples as an .nt file (one line per row)."""
    with open(path, "w") as f:
        for s, p, o in triples_to_tokens(triples):
            f.write(f"{s} {p} {o} .\n")


def grow_delta_batches(triples: np.ndarray, frac: float, seed: int = 0):
    """Grow an insert/delete script touching ~`frac` of the workload.

    Returns (inserts, deletes): `deletes` are rows sampled from `triples`
    (each retracts one line), `inserts` are half recombinations of existing
    values (perturbing existing join lines) and half rows over brand-new
    ids past the current maximum (minting new dictionary values — and with
    them new buckets — in the delta run).  Row counts split the change
    budget evenly; at least one of each when frac > 0."""
    t = np.asarray(triples, np.int64)
    n = t.shape[0]
    rng = np.random.default_rng(seed)
    n_changes = max(2, int(round(n * frac)))
    n_del = max(1, n_changes // 2)
    n_ins = max(1, n_changes - n_del)
    deletes = t[rng.choice(n, size=min(n_del, n), replace=False)]
    n_recomb = n_ins // 2
    pool = np.unique(t.reshape(-1))
    recomb = rng.choice(pool, size=(n_recomb, 3))
    base = int(t.max()) + 1 if n else 0
    fresh = base + np.arange((n_ins - n_recomb) * 3,
                             dtype=np.int64).reshape(-1, 3)
    inserts = np.concatenate([recomb.reshape(-1, 3), fresh])
    return inserts.astype(np.int64), deletes.astype(np.int64)


def apply_delta(triples: np.ndarray, inserts: np.ndarray,
                deletes: np.ndarray) -> np.ndarray:
    """The updated dataset a from-scratch comparator runs on: multiset
    minus one occurrence per delete row, plus the insert rows (mirrors the
    delta engine's bag semantics)."""
    t = [tuple(r) for r in np.asarray(triples, np.int64).tolist()]
    from collections import Counter
    pending = Counter(map(tuple, np.asarray(deletes, np.int64).tolist()))
    kept = []
    for row in t:
        if pending.get(row, 0) > 0:
            pending[row] -= 1
            continue
        kept.append(row)
    out = kept + [tuple(r) for r in np.asarray(inserts, np.int64).tolist()]
    return (np.asarray(out, np.int64).reshape(-1, 3)
            if out else np.zeros((0, 3), np.int64))
