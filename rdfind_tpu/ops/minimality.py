"""Implied-CIND removal (--clean-implied) as device sort-merge joins.

The reference's minimality cleanup is four distributed coGroups
(plan/TraversalStrategy.scala:126-168 with RemoveNonMinimalDoubleXxxCinds /
RemoveNonMinimalXxxSingleCinds): a CIND is dropped when a *directly* implying
CIND exists —

  pass A: a 2/1 whose dep has a unary subcapture forming a 1/1 with the same ref;
  pass B: a 2/1 whose ref is a unary subcapture of a 2/2's ref with the same dep;
  pass C: a 1/1 whose ref is a unary subcapture of a 1/2's ref with the same dep;
  pass D: a 2/2 whose dep has a unary subcapture forming a 1/2 with the same ref.

All 1/2 CINDs are kept, and only direct implications are checked (the
reference's documented limitation) — oracle.minimize_cinds is the independent
host-set-algebra cross-check, used by tests only.

TPU formulation: all four passes are membership tests of 6-column keys, so
they fuse into ONE tag-sorted merge join — keys carry a pass-id column, the
implying side and the query side are each 6 fixed n-row segments, and a single
masked_unique + masked_table_index answers every pass at once (one device sort
over 12n rows instead of 4 hash joins).

Sharded (--dop > 1): both sides are hash-partitioned by key to their owner
device (parallel/exchange.route), the owner joins locally, and verdicts ride
the reply collective back to the asking rows — the coGroup recast as a
fixed-capacity exchange with the usual measured-capacity + overflow-retry
contract.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import conditions as cc
from ..data import NO_VALUE, CindTable
from . import hashing, segments

_N_SEG = 6  # key segments per side (see _implying_keys/_query_keys)


def _families(dep_code, ref_code, valid):
    dep_bin = cc.is_binary(dep_code)
    ref_bin = cc.is_binary(ref_code)
    return dict(
        f11=valid & ~dep_bin & ~ref_bin,
        f12=valid & ~dep_bin & ref_bin,
        f21=valid & dep_bin & ~ref_bin,
        f22=valid & dep_bin & ref_bin,
    )


def _implying_keys(cols, fam):
    """(7 key columns, valid) of the implying side: 6 segments of n rows.

    Segment layout (pass id first key column):
      A (0): 1/1 rows as   (ref, dep)
      B (1): 2/2 rows as   (dep, ref-subcapture-q)   for q = 1, 2
      C (2): 1/2 rows as   (dep, ref-subcapture-q)   for q = 1, 2
      D (3): 1/2 rows as   (ref, dep)
    """
    dc, d1, d2, rc, r1, r2 = cols
    no_v = jnp.full_like(d1, NO_VALUE)
    sub1_r, sub2_r = cc.first_subcapture(rc), cc.second_subcapture(rc)
    segs = [
        (0, fam["f11"], (rc, r1, r2, dc, d1, d2)),
        (1, fam["f22"], (dc, d1, d2, sub1_r, r1, no_v)),
        (1, fam["f22"], (dc, d1, d2, sub2_r, r2, no_v)),
        (2, fam["f12"], (dc, d1, d2, sub1_r, r1, no_v)),
        (2, fam["f12"], (dc, d1, d2, sub2_r, r2, no_v)),
        (3, fam["f12"], (rc, r1, r2, dc, d1, d2)),
    ]
    return _stack_segments(segs)


def _query_keys(cols, fam):
    """(7 key columns, valid) of the query side: 6 segments of n rows.

    Segment layout (matches _implying_keys pass ids):
      A (0): 2/1 rows as   (ref, dep-subcapture-q)   for q = 1, 2
      B (1): 2/1 rows as   (dep, ref)
      C (2): 1/1 rows as   (dep, ref)
      D (3): 2/2 rows as   (ref, dep-subcapture-q)   for q = 1, 2
    """
    dc, d1, d2, rc, r1, r2 = cols
    no_v = jnp.full_like(d1, NO_VALUE)
    sub1_d, sub2_d = cc.first_subcapture(dc), cc.second_subcapture(dc)
    segs = [
        (0, fam["f21"], (rc, r1, r2, sub1_d, d1, no_v)),
        (0, fam["f21"], (rc, r1, r2, sub2_d, d2, no_v)),
        (1, fam["f21"], (dc, d1, d2, rc, r1, r2)),
        (2, fam["f11"], (dc, d1, d2, rc, r1, r2)),
        (3, fam["f22"], (rc, r1, r2, sub1_d, d1, no_v)),
        (3, fam["f22"], (rc, r1, r2, sub2_d, d2, no_v)),
    ]
    return _stack_segments(segs)


def _stack_segments(segs):
    n = segs[0][2][0].shape[0]
    pass_col = jnp.concatenate(
        [jnp.full(n, p, jnp.int32) for p, _, _ in segs])
    key_cols = [pass_col] + [
        jnp.concatenate([s[2][i] for s in segs]) for i in range(6)]
    valid = jnp.concatenate([s[1] for s in segs])
    return key_cols, valid


def _keep_from_found(found, fam, valid, n):
    """Fold the 6 query-segment verdicts back to a per-row keep mask."""
    seg = [found[i * n:(i + 1) * n] for i in range(_N_SEG)]
    killed = (seg[0] | seg[1] | seg[2]   # 2/1 via pass A (two subqueries) + B
              | seg[3]                   # 1/1 via pass C
              | seg[4] | seg[5])         # 2/2 via pass D (two subqueries)
    return valid & ~killed


@jax.jit
def _stage_keep_mask(dep_code, dep_v1, dep_v2, ref_code, ref_v1, ref_v2,
                     n_valid):
    """Single-device keep mask over pow2-padded columns."""
    n = dep_code.shape[0]
    valid = jnp.arange(n, dtype=jnp.int32) < n_valid
    cols = (dep_code, dep_v1, dep_v2, ref_code, ref_v1, ref_v2)
    fam = _families(dep_code, ref_code, valid)
    imp_cols, imp_valid = _implying_keys(cols, fam)
    qry_cols, qry_valid = _query_keys(cols, fam)
    tab_cols, _, _, n_tab = segments.masked_unique(imp_cols, imp_valid)
    found = segments.masked_table_index(tab_cols, n_tab, qry_cols,
                                        qry_valid) >= 0
    return _keep_from_found(found, fam, valid, n)


def _pad_cols(table: CindTable):
    """CindTable -> (6 pow2-padded int32 device columns, n)."""
    n = len(table)
    cap = segments.pow2_capacity(n)
    out = []
    for c in (table.dep_code, table.dep_v1, table.dep_v2,
              table.ref_code, table.ref_v1, table.ref_v2):
        a = np.full(cap, segments.SENTINEL, np.int32)
        a[:n] = np.asarray(c, np.int64).astype(np.int32)
        out.append(jnp.asarray(a))
    return out, n


def _apply_keep(table: CindTable, keep: np.ndarray) -> CindTable:
    return CindTable(*(np.asarray(c)[keep] for c in (
        table.dep_code, table.dep_v1, table.dep_v2,
        table.ref_code, table.ref_v1, table.ref_v2, table.support)))


def implication_possible(table: CindTable) -> bool:
    """Whether any row of `table` can be killed by passes A-D at all.

    The minimality pre-filter of the fused dense sweep (ISSUE 6 rung 2):
    each pass joins a query family against an implying family (A: 2/1 vs
    1/1, B: 2/1 vs 2/2, C: 1/1 vs 1/2, D: 2/2 vs 1/2), so when no (query,
    implying) family pair co-occurs the whole device sort-merge join is a
    provable no-op and is skipped.  Host family counts are a handful of
    numpy popcounts over the code columns — negligible next to the padded
    12n-row device sort they avoid.  Output-neutral by construction.
    """
    dep_bin = np.asarray(cc.is_binary(np.asarray(table.dep_code)))
    ref_bin = np.asarray(cc.is_binary(np.asarray(table.ref_code)))
    n11 = int((~dep_bin & ~ref_bin).sum())
    n12 = int((~dep_bin & ref_bin).sum())
    n21 = int((dep_bin & ~ref_bin).sum())
    n22 = int((dep_bin & ref_bin).sum())
    # A: n11 implying x n21 query; B: n22 x n21; C: n12 x n11; D: n12 x n22.
    return bool((n21 or n12) and (n11 or n22))


def minimize_table(table: CindTable) -> CindTable:
    """Drop implied CINDs (device sort-merge join; single device)."""
    if len(table) == 0 or not implication_possible(table):
        return table
    cols, n = _pad_cols(table)
    keep = np.asarray(_stage_keep_mask(*cols, jnp.int32(n)))[:n]
    return _apply_keep(table, keep)


# --------------------------------------------------------------------------
# Sharded variant: hash-partitioned membership join over the mesh.
# --------------------------------------------------------------------------


import functools


@functools.lru_cache(maxsize=None)
def _stage_keep_sharded(mesh, capacity: int):
    """Compiled shard_map program: (D*blk,) row-sharded columns -> keep mask.

    Each device builds the key segments for its row block, routes both sides
    to the key's hash owner, joins there, and pulls the verdicts back via the
    reply collective.  Returns (keep, overflow).
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel import exchange
    from ..parallel.mesh import AXIS, shard_map

    def f(dc, d1, d2, rc, r1, r2, valid):
        n = dc.shape[0]
        cols = (dc, d1, d2, rc, r1, r2)
        fam = _families(dc, rc, valid)
        imp_cols, imp_valid = _implying_keys(cols, fam)
        qry_cols, qry_valid = _query_keys(cols, fam)
        d = jax.lax.psum(1, AXIS)
        imp_bkt = hashing.bucket_of(imp_cols, d, seed=11)
        qry_bkt = hashing.bucket_of(qry_cols, d, seed=11)
        recv_imp, recv_imp_v, ovf_i, _ = exchange.route(
            imp_cols, imp_valid, imp_bkt, AXIS, capacity)
        recv_qry, recv_qry_v, ovf_q, state = exchange.route(
            qry_cols, qry_valid, qry_bkt, AXIS, capacity)
        tab_cols, _, _, n_tab = segments.masked_unique(recv_imp, recv_imp_v)
        found = (segments.masked_table_index(tab_cols, n_tab, recv_qry,
                                             recv_qry_v) >= 0)
        back = exchange.route_reply(found.astype(jnp.int32), state, AXIS) == 1
        keep = _keep_from_found(back, fam, valid, n)
        return keep, ovf_i + ovf_q

    return jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(P(AXIS),) * 7,
        out_specs=(P(AXIS), P())))


def minimize_table_sharded(table: CindTable, mesh) -> CindTable:
    """Drop implied CINDs with the join hash-partitioned over `mesh`.

    The four coGroups run as one fixed-capacity exchange per side; capacity is
    planned from the expected per-owner volume and doubled on overflow (the
    capacity-plan/retry contract every sharded exchange follows).
    """
    n = len(table)
    # The family pre-filter is computed from the replicated host table, so
    # every process takes the same branch — no collective is skipped on one
    # host only.
    if n == 0 or not implication_possible(table):
        return table
    num_dev = mesh.devices.size
    if num_dev == 1:
        return minimize_table(table)

    blk = max(64, segments.pow2_capacity(-(-n // num_dev)))
    cols = []
    for c in (table.dep_code, table.dep_v1, table.dep_v2,
              table.ref_code, table.ref_v1, table.ref_v2):
        a = np.full(num_dev * blk, segments.SENTINEL, np.int32)
        a[:n] = np.asarray(c, np.int64).astype(np.int32)
        cols.append(a)
    valid = np.zeros(num_dev * blk, bool)
    valid[:n] = True

    # Each side is 6 segments of blk rows per device; hashing spreads them
    # evenly, so per-(src, dst) volume ~ 6*blk/D.
    capacity = segments.pow2_capacity(
        max(64, (6 * blk) // num_dev + (6 * blk) // (num_dev * 4)))
    from ..parallel.mesh import host_gather, make_global

    max_retries = 4
    for _ in range(max_retries):
        if num_dev * capacity > (1 << 31) - 1:
            # route()'s (D * capacity) flat index is int32; wrapping it would
            # silently corrupt the keep mask, so fail the way every other
            # planned exchange does.
            raise RuntimeError(
                f"minimality exchange capacity {capacity} x {num_dev} "
                f"devices exceeds the int32 buffer budget; rerun with more "
                f"devices")
        prog = _stage_keep_sharded(mesh, capacity)
        # make_global: each process donates only the rows its devices own
        # (device_put of a host array is single-process-only).
        args = [make_global(c, mesh) for c in cols] + [
            make_global(valid, mesh)]
        keep, ovf = prog(*args)
        ovf = int(np.asarray(host_gather(ovf)).reshape(-1)[0])
        if ovf == 0:
            break
        capacity = segments.pow2_capacity(2 * capacity + ovf)
    else:
        raise RuntimeError(
            f"minimality exchange overflow persisted after {max_retries} "
            f"retries (ovf={ovf})")
    keep = np.asarray(host_gather(keep)).reshape(-1)[:n]
    return _apply_keep(table, keep)
