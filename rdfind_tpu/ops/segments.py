"""Sorted-run / segment algebra — the TPU-side replacement for hash shuffles.

Every Flink ``groupBy`` in the reference plan (RDFind.scala:332-346,
AllAtOnceTraversalStrategy.scala:60-68) becomes: lexicographic sort of int32 key
columns + run detection + segment reduction.  All indices stay int32 (no x64 needed),
shapes stay static per input size, and the sorts map onto XLA's TPU sort.
"""

from __future__ import annotations

import jax.lax
import jax.numpy as jnp


def lexsort(cols):
    """Permutation sorting rows by the given key columns (first column = major key).

    `cols` is a sequence of equal-length 1-D arrays.  Returns int32 indices.
    jnp.lexsort takes the *last* key as primary, so reverse here.
    """
    return jnp.lexsort(tuple(reversed(tuple(cols))))


def run_starts(sorted_cols):
    """Boolean mask marking the first row of each distinct-key run in sorted rows."""
    n = sorted_cols[0].shape[0]
    if n == 0:
        return jnp.zeros(0, bool)
    neq = jnp.zeros(n - 1, bool)
    for c in sorted_cols:
        neq = neq | (c[1:] != c[:-1])
    return jnp.concatenate([jnp.ones(1, bool), neq])


# ---------------------------------------------------------------------------
# Jit-safe (fixed-shape, mask-based) variants.  Convention: invalid rows carry
# SENTINEL in every key column, so they sort to the end and form one garbage run.
# ---------------------------------------------------------------------------

SENTINEL = jnp.iinfo(jnp.int32).max


def pow2_capacity(n: int) -> int:
    """Smallest power of two >= n (>= 1): the capacity-bucketing policy that keeps
    compiled stage programs reusable across datasets."""
    return 1 << max(0, int(n) - 1).bit_length() if n > 1 else 1


def masked_row_counts(cols, valid):
    """For each row, how many valid rows share its key.  Fixed-shape, jittable.

    Invalid rows get count 0.
    """
    n = cols[0].shape[0]
    cols = [jnp.where(valid, c, SENTINEL) for c in cols]
    perm = lexsort(cols)
    sorted_cols = [c[perm] for c in cols]
    v_sorted = valid[perm].astype(jnp.int32)
    gid = jnp.cumsum(run_starts(sorted_cols)).astype(jnp.int32) - 1
    counts = jax.ops.segment_sum(v_sorted, gid, num_segments=n)
    per_row_sorted = counts[gid] * v_sorted
    return jnp.zeros(n, jnp.int32).at[perm].set(per_row_sorted)


def masked_weighted_row_counts(cols, weights, valid):
    """For each row, the sum of `weights` over valid rows sharing its key.

    The weighted generalization of masked_row_counts — the reduce side of a
    distributed count whose combiner pre-summed local multiplicities.  Invalid
    rows get 0.
    """
    n = cols[0].shape[0]
    cols = [jnp.where(valid, c, SENTINEL) for c in cols]
    perm = lexsort(cols)
    sorted_cols = [c[perm] for c in cols]
    v_sorted = valid[perm]
    w_sorted = jnp.where(v_sorted, weights[perm], 0).astype(jnp.int32)
    gid = jnp.cumsum(run_starts(sorted_cols)).astype(jnp.int32) - 1
    sums = jax.ops.segment_sum(w_sorted, gid, num_segments=n)
    per_row_sorted = sums[gid] * v_sorted.astype(jnp.int32)
    return jnp.zeros(n, jnp.int32).at[perm].set(per_row_sorted)


def masked_unique(cols, valid):
    """Distinct valid rows, compacted to the front in sorted key order.

    Returns (out_cols, out_valid, inverse, n_unique):
      out_cols  -- fixed-shape columns; rows [0, n_unique) are the distinct keys in
                   ascending order, the rest is garbage;
      inverse   -- for each input row, the dense id of its key (garbage for invalid
                   rows);
      n_unique  -- scalar array, number of distinct valid keys.
    """
    n = cols[0].shape[0]
    cols = [jnp.where(valid, c, SENTINEL) for c in cols]
    perm = lexsort(cols)
    sorted_cols = [c[perm] for c in cols]
    v_sorted = valid[perm]
    is_new = run_starts(sorted_cols) & v_sorted
    gid = jnp.cumsum(is_new).astype(jnp.int32) - 1  # valid rows only; garbage run inherits last id
    n_unique = is_new.sum().astype(jnp.int32)
    inverse = jnp.zeros(n, jnp.int32).at[perm].set(gid)
    # Compact distinct rows to the front, preserving sorted order: scatter each
    # first-of-run row to its dense id (gid increments in sorted order), which
    # replaces a full argsort with one scatter.  Rows >= n_unique are SENTINEL.
    target = jnp.where(is_new, gid, n)
    out_cols = [jnp.full(n, SENTINEL, c.dtype).at[target].set(c, mode="drop")
                for c in sorted_cols]
    out_valid = jnp.arange(n, dtype=jnp.int32) < n_unique
    return out_cols, out_valid, inverse, n_unique


def masked_table_index(table_cols, n_table, query_cols, query_valid):
    """For each query row, the index of its key in a sorted table, else -1.

    table_cols: valid-prefix columns, rows [0, n_table) sorted ascending and
    distinct (masked_unique output shape).  A sort-merge join: table rows tag 0
    sort before equal-key query rows, so each run's first element carries the
    table index for the whole run.  Invalid/absent queries get -1.
    """
    n_t = table_cols[0].shape[0]
    n_q = query_cols[0].shape[0]
    t_valid = jnp.arange(n_t, dtype=jnp.int32) < n_table
    cols = [jnp.concatenate([jnp.where(t_valid, tc, SENTINEL),
                             jnp.where(query_valid, qc, SENTINEL)])
            for tc, qc in zip(table_cols, query_cols)]
    # Tags: valid table 0 < query 1 < invalid table 2, so the SENTINEL garbage
    # run can never begin with a padded table row (which would donate a bogus
    # index to invalid queries).
    tag = jnp.concatenate([jnp.where(t_valid, 0, 2).astype(jnp.int32),
                           jnp.ones(n_q, jnp.int32)])
    perm = lexsort(cols + [tag])
    starts = run_starts([c[perm] for c in cols])
    idx = jnp.arange(n_t + n_q, dtype=jnp.int32)
    start_pos = jax.lax.cummax(jnp.where(starts, idx, 0))
    first_orig = perm[start_pos]
    run_idx = jnp.where(first_orig < n_t, first_orig, -1)
    out = jnp.zeros(n_t + n_q, jnp.int32).at[perm].set(run_idx)
    return jnp.where(query_valid, out[n_t:], -1)


def masked_dense_ids(col, valid):
    """Dense ids (0..n_ids-1, in ascending key order) for one key column.

    The light sibling of masked_unique for callers that need only the inverse
    mapping and the count — skips the compaction argsort and the unique-row
    columns (one sort pass total).  Invalid rows get a garbage id; mask them.
    """
    n = col.shape[0]
    key = jnp.where(valid, col, SENTINEL)
    perm = jnp.argsort(key)
    v_sorted = valid[perm]
    is_new = run_starts([key[perm]]) & v_sorted
    gid = jnp.cumsum(is_new).astype(jnp.int32) - 1
    inverse = jnp.zeros(n, jnp.int32).at[perm].set(gid)
    return inverse, is_new.sum().astype(jnp.int32)


def compact(cols, keep):
    """Move rows with keep=True to the front (preserving order).  Jittable.

    Returns (out_cols, n_kept).
    """
    order = jnp.argsort(~keep, stable=True)
    return [c[order] for c in cols], keep.sum().astype(jnp.int32)
