"""Frequent-condition mining + association rules as segment counting.

Replaces the reference's FrequentConditionPlanner count pipelines
(plan/FrequentConditionPlanner.scala:291-311 unary, :374-394 binary, :130-194
association rules): a condition (field=value, or field-pair=value-pair) is
*frequent* when at least ``min_support`` triples satisfy it.  Frequency is a
conservative prefilter — a capture can never be larger than its condition's triple
count — so pruning on it never changes the final CIND set (the exact support test
happens downstream).

Instead of Bloom filters broadcast to workers, counts are computed exactly via
group-by-and-count and mapped straight back onto the triple rows that asked — the
query set and the count set are the same rows, so membership testing disappears.
The same trick makes association rules *local*: the perfect-confidence test for the
rule (a=va) -> (b=vb) is count(a=va ∧ b=vb) == count(a=va), evaluable per triple row
from the group counts — no rule broadcast needed at emission time.

Fixed-shape and jittable: `valid` masks padding rows, which always count as 0.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import conditions as cc
from . import segments

_FIELD_PAIRS = ((0, 1), (0, 2), (1, 2))  # (s,p), (s,o), (p,o) in ascending bit order
_FIELD_BITS = (cc.SUBJECT, cc.PREDICATE, cc.OBJECT)


@dataclasses.dataclass
class TripleFrequency:
    """Per-triple-row frequency verdicts.

    unary_ok[i, f]       -- field f's value in row i occurs >= min_support times;
    binary_ok[i, k]      -- row i's value pair for field-pair k occurs >= min_support
                            times (k indexes _FIELD_PAIRS);
    binary_ar_implied[i, k] -- the pair condition is implied by a perfect-confidence
                            association rule (either direction), i.e. the binary
                            capture equals one of its unary halves extensionally.
    """

    unary_ok: jnp.ndarray  # (N, 3) bool
    binary_ok: jnp.ndarray  # (N, 3) bool
    binary_ar_implied: jnp.ndarray  # (N, 3) bool


def triple_frequencies(triples, valid, min_support,
                       find_ar_implied: bool = False) -> TripleFrequency:
    """Exact unary + binary condition frequencies, evaluated on the triples' own rows."""
    unary_cnt = [segments.masked_row_counts([triples[:, f]], valid) for f in range(3)]
    binary_cnt = [segments.masked_row_counts([triples[:, a], triples[:, b]], valid)
                  for a, b in _FIELD_PAIRS]
    unary_ok = jnp.stack([c >= min_support for c in unary_cnt], axis=1)
    binary_ok = jnp.stack([c >= min_support for c in binary_cnt], axis=1)
    if find_ar_implied:
        # Rule (a -> b) or (b -> a) with confidence 1 over frequent conditions:
        # emission then suppresses the redundant binary capture
        # (CreateJoinPartners.scala:100-146 with the AR broadcast).
        ar = jnp.stack([
            (binary_cnt[k] == unary_cnt[a]) | (binary_cnt[k] == unary_cnt[b])
            for k, (a, b) in enumerate(_FIELD_PAIRS)
        ], axis=1) & binary_ok
    else:
        ar = jnp.zeros_like(binary_ok)
    return TripleFrequency(unary_ok=unary_ok, binary_ok=binary_ok,
                           binary_ar_implied=ar)


def no_filter(valid) -> TripleFrequency:
    """All-pass verdicts for valid rows (the --no-frequent-item-set path)."""
    ok = jnp.tile(valid[:, None], (1, 3))
    return TripleFrequency(ok, ok, jnp.zeros_like(ok))


def emit_rule_rows(triples, valid, min_support, unary_counts, binary_counts):
    """Distinct perfect-confidence rule rows from per-row condition counts.

    Shared emitter for the host and the distributed miners (they differ only
    in where counts come from: local segment counts vs the count exchange).
    unary_counts[f] / binary_counts[k] are (N,) per-row counts of field f's
    value / field-pair k's value pair.  Returns (cols, valid): five fixed-shape
    columns (ant_bit, cons_bit, ant_val, cons_val, support) with the distinct
    rule rows compacted to the front.
    """
    n = triples.shape[0]
    parts = []
    for k, (a, b) in enumerate(_FIELD_PAIRS):
        cnt_ab = binary_counts[k]
        for ant, con, cnt_u in ((a, b, unary_counts[a]), (b, a, unary_counts[b])):
            is_rule = valid & (cnt_ab == cnt_u) & (cnt_u >= min_support)
            parts.append((jnp.full(n, _FIELD_BITS[ant], jnp.int32),
                          jnp.full(n, _FIELD_BITS[con], jnp.int32),
                          triples[:, ant], triples[:, con], cnt_ab, is_rule))
    cols = [jnp.concatenate([p[i] for p in parts]) for i in range(5)]
    mask = jnp.concatenate([p[5] for p in parts])
    # Support (cnt_ab) is constant within a rule group, so it can ride along as a
    # fifth key column without affecting uniqueness.
    (full_cols, u_valid, _, n_rules) = segments.masked_unique(cols, mask)
    return full_cols, u_valid, n_rules


@jax.jit
def _stage_rules(triples, n_valid, min_support):
    """All perfect-confidence association rules, compacted to the front.

    Returns (ant_bit, cons_bit, ant_val, cons_val, support, n_rules): one row per
    directed rule (a=va) -> (b=vb) with count(a=va ∧ b=vb) == count(a=va) and the
    antecedent frequent (FrequentConditionPlanner.scala:130-194; the consequent is
    then automatically frequent).
    """
    n = triples.shape[0]
    valid = jnp.arange(n, dtype=jnp.int32) < n_valid
    unary = [segments.masked_row_counts([triples[:, f]], valid)
             for f in range(3)]
    binary = [segments.masked_row_counts([triples[:, a], triples[:, b]], valid)
              for a, b in _FIELD_PAIRS]
    full_cols, _, n_rules = emit_rule_rows(triples, valid, min_support,
                                           unary, binary)
    return (*full_cols, n_rules)


def ar_implied_pair_mask(dep_code, ref_code, dep_v1, ref_v1, mined_rules):
    """True where a capture pair restates a mined perfect-confidence rule.

    Host-side, shared by every strategy's AR post-filter
    (FilterAssociationRuleImpliedCinds.scala:30-58): the suppressed pairs are
    unary/unary with the same projection, whose (antecedent-field, consequent-field,
    antecedent-value, consequent-value) matches a rule.
    """
    dep_code = np.asarray(dep_code)
    ref_code = np.asarray(ref_code)
    out = np.zeros(len(dep_code), bool)
    ants, cons, avs, cvs, _ = mined_rules
    if len(ants) == 0 or len(dep_code) == 0:
        return out
    cand = np.flatnonzero(
        np.asarray(cc.is_unary(dep_code) & cc.is_unary(ref_code)
                   & (cc.secondary(dep_code) == cc.secondary(ref_code))
                   & (cc.primary(dep_code) != cc.primary(ref_code))))
    if cand.size == 0:
        return out
    dep_v1 = np.asarray(dep_v1)
    ref_v1 = np.asarray(ref_v1)
    # Membership of (ant_field, cons_field, ant_val, cons_val) rows in the rule
    # table via one row-wise unique — a sorted join, no per-row interpreter work.
    rule_rows = np.stack([ants, cons, avs, cvs], axis=1).astype(np.int64)
    cand_rows = np.stack([
        np.asarray(cc.primary(dep_code[cand]), np.int64),
        np.asarray(cc.primary(ref_code[cand]), np.int64),
        dep_v1[cand].astype(np.int64), ref_v1[cand].astype(np.int64)], axis=1)
    allr = np.concatenate([rule_rows, cand_rows])
    uniq, inv = np.unique(allr, axis=0, return_inverse=True)
    in_rules = np.zeros(len(uniq), bool)
    in_rules[inv[:len(rule_rows)]] = True
    out[cand] = in_rules[inv[len(rule_rows):]]
    return out


@functools.partial(jax.jit, static_argnames="field_groups")
def _stage_count_fcs(triples, n_valid, min_support, field_groups):
    """Distinct frequent conditions over `field_groups`, summed (device-side)."""
    n = triples.shape[0]
    valid = jnp.arange(n, dtype=jnp.int32) < n_valid
    total = jnp.int32(0)
    for fields in field_groups:
        cols = [triples[:, f] for f in fields]
        cnt = segments.masked_row_counts(cols, valid)
        ok = valid & (cnt >= min_support)
        _, _, _, n_u = segments.masked_unique(cols, ok)
        total += n_u
    return total


def _pad_to_device(triples_np):
    """(N, 3) int32 -> pow2-padded device array (SENTINEL-padded rows)."""
    n = triples_np.shape[0]
    cap = segments.pow2_capacity(n)
    padded = np.pad(triples_np, ((0, cap - n), (0, 0)),
                    constant_values=np.iinfo(np.int32).max)
    return jnp.asarray(padded)


def count_frequent_conditions(triples_np, min_support: int,
                              include_binary: bool):
    """Distinct frequent unary (and optionally binary) condition counts.

    The --find-only-fcs report path (RDFind.scala:298-306: level >= 1 emits the
    single-condition filters and stops; level >= 2 additionally emits the
    double-condition filters).  Runs on the same device segment-count ops as
    the real pipeline so the flag exercises the production frequency code.
    Returns (n_unary, n_binary) with n_binary None when not requested.
    """
    n = triples_np.shape[0]
    if n == 0:
        return 0, (0 if include_binary else None)
    dev = _pad_to_device(triples_np)
    ms = jnp.int32(max(int(min_support), 1))
    n_unary = int(_stage_count_fcs(dev, jnp.int32(n), ms, ((0,), (1,), (2,))))
    n_binary = (int(_stage_count_fcs(dev, jnp.int32(n), ms, _FIELD_PAIRS))
                if include_binary else None)
    return n_unary, n_binary


def mine_association_rules(triples_np, min_support: int):
    """Host wrapper: (N, 3) int32 -> numpy rule table (ant_bit, cons_bit, ant_val,
    cons_val, support)."""
    n = triples_np.shape[0]
    if n == 0:
        return [np.zeros(0, np.int32)] * 5
    out = _stage_rules(_pad_to_device(triples_np), jnp.int32(n),
                       jnp.int32(max(int(min_support), 1)))
    *cols, n_rules = out
    n_rules = int(n_rules)
    return [np.asarray(c[:n_rules]) for c in cols]
