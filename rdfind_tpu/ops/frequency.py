"""Frequent-condition mining as segment counting.

Replaces the reference's FrequentConditionPlanner count pipelines
(plan/FrequentConditionPlanner.scala:291-311 for unary, :374-394 for binary): a
condition (field=value, or field-pair=value-pair) is *frequent* when at least
``min_support`` triples satisfy it.  Frequency here is a conservative prefilter — a
capture can never be larger than its condition's triple count — so pruning on it
never changes the final CIND set (the exact support test happens downstream).

Instead of Bloom filters broadcast to workers, counts are computed exactly via
group-by-and-count and mapped straight back onto the triple rows that asked — the
query set and the count set are the same rows, so membership testing disappears.

Fixed-shape and jittable: `valid` masks padding rows, which always count as 0.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from . import segments

_FIELD_PAIRS = ((0, 1), (0, 2), (1, 2))  # (s,p), (s,o), (p,o) in ascending bit order


@dataclasses.dataclass
class TripleFrequency:
    """Per-triple-row frequency verdicts.

    unary_ok[i, f]   -- field f's value in row i occurs >= min_support times in f;
    binary_ok[i, k]  -- row i's value pair for field-pair k occurs >= min_support
                        times (k indexes _FIELD_PAIRS).
    """

    unary_ok: jnp.ndarray  # (N, 3) bool
    binary_ok: jnp.ndarray  # (N, 3) bool


def triple_frequencies(triples, valid, min_support) -> TripleFrequency:
    """Exact unary + binary condition frequencies, evaluated on the triples' own rows."""
    unary_ok = [
        segments.masked_row_counts([triples[:, f]], valid) >= min_support
        for f in range(3)
    ]
    binary_ok = [
        segments.masked_row_counts([triples[:, a], triples[:, b]], valid) >= min_support
        for a, b in _FIELD_PAIRS
    ]
    return TripleFrequency(
        unary_ok=jnp.stack(unary_ok, axis=1),
        binary_ok=jnp.stack(binary_ok, axis=1),
    )


def no_filter(valid) -> TripleFrequency:
    """All-pass verdicts for valid rows (the --no-frequent-item-set path)."""
    ok = jnp.tile(valid[:, None], (1, 3))
    return TripleFrequency(ok, ok)
