"""Pallas TPU kernels for the packed-bitset hot path.

The reference's compute-critical "native" surface is its forked-Guava Bloom
filter (SURVEY.md §2: exportBits/intersect + SpectralBloomFilter) — the bit
twiddling under every approximate strategy.  Here that surface is the packed
(rows × bits/32) uint32 sketch matrix (ops/sketch.py), and its hot op is the
containment matmul: "which hash-bit sets are fully contained in which sketch
rows", for all (dep, ref) pairs at once.

The jnp path (sketch.contains_matrix) unpacks both sides to full 0/1 planes in
HBM — a 32x write + read amplification of pure memory traffic — before the MXU
contraction.  The kernel below never materializes planes: each grid step DMAs a
packed (TILE, WK) uint32 tile into VMEM, unpacks it in-register, and feeds the
MXU with a (TILE, WK*32) contraction, accumulating across word chunks in an f32
VMEM scratch.  HBM traffic drops to the packed bytes.

Layout notes (see /opt/skills/guides/pallas_guide.md): Mosaic cannot slice the
lane dimension at non-128-aligned offsets, so the unpack avoids slicing
entirely: `pltpu.repeat(x, 32, axis=1)` tiles the packed words 32x along lanes
(np.tile semantics: lane j holds word j % WK), and the per-lane shift is
j // WK.  That yields planes in *bit-major* lane order — a fixed permutation of
the contraction dimension, harmless because both operands unpack identically
and the dot product is permutation-invariant.  uint32->bf16 needs a two-step
cast through int32 (Mosaic has no direct lowering, r2 bench failure).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_D = 128
TILE_R = 128
# Words per K grid step: 128 words = 4096 contraction lanes = 1 MB of unpacked
# bf16 per operand tile in VMEM, well under budget while keeping the MXU fed.
WK_MAX = 128


@functools.lru_cache(maxsize=1)
def _repeat_is_tile() -> bool:
    """Whether this jax's pltpu.repeat follows np.tile lane order (newer
    versions: lane j holds word j % WK) or np.repeat order (older: lane j
    holds word j // n).  The unpack's shift formula must match, or the
    planes stop being a permutation of the bits and the containment counts
    go silently wrong.  Probed once through the interpreter, which agrees
    with the Mosaic lowering within a jax version."""
    try:
        def k(x_ref, o_ref):
            o_ref[:] = pltpu.repeat(x_ref[:], 2, axis=1)

        # The first call can land inside an outer jit/pallas trace (the
        # kernel is traced lazily); escape it so the probe runs eagerly —
        # staged, its output would be a tracer and the comparison would
        # bogusly take the except path.
        with jax.ensure_compile_time_eval():
            out = pl.pallas_call(
                k, out_shape=jax.ShapeDtypeStruct((1, 4), jnp.int32),
                interpret=True)(jnp.arange(2, dtype=jnp.int32).reshape(1, 2))
            host = [int(v) for v in np.asarray(out)[0]]
        return host == [0, 1, 0, 1]
    except Exception:
        return True  # current upstream semantics


def _unpack_tile(x):
    """(TILE, WK) packed uint32 -> (TILE, WK*32) 0/1 bf16 planes.

    Lane j of the result is bit (j // WK) of word (j % WK) under tile-order
    repeat, or bit (j % 32) of word (j // 32) under repeat-order — either is
    a fixed permutation of the bits, harmless because both operands unpack
    identically and the dot product is permutation-invariant.  Only
    full-tile ops: repeat, iota, shift, compare — no lane slicing (Mosaic
    requires lane-dim slice offsets to be 128-aligned, which word steps are
    not).
    """
    wk = x.shape[1]
    rep = pltpu.repeat(x, 32, axis=1)
    lane = jax.lax.broadcasted_iota(jnp.uint32, rep.shape, 1)
    shifts = (jax.lax.div(lane, jnp.uint32(wk)) if _repeat_is_tile()
              else jax.lax.rem(lane, jnp.uint32(32)))
    return ((rep >> shifts) & jnp.uint32(1)).astype(jnp.int32).astype(jnp.bfloat16)


def _contains_kernel(s_ref, r_ref, popc_ref, out_ref, acc_ref):
    """One (TILE_D, TILE_R) tile of the containment matrix.

    s_ref: (TILE_D, WK) packed dep sketches; r_ref: (TILE_R, WK) packed ref bit
    sets; popc_ref: (1, TILE_R) per-ref set bit counts.  out[d, r] = 1 iff every
    set bit of ref r is set in sketch d, tested as <unpacked s, unpacked r> ==
    popcount(r) — the same MXU formulation as the jnp path, minus the HBM
    planes.  The K grid dim accumulates word chunks into acc_ref.
    """
    k = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s_b = _unpack_tile(s_ref[:])
    r_b = _unpack_tile(r_ref[:])
    acc_ref[:] += jax.lax.dot_general(
        s_b, r_b, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _finalize():
        out_ref[:] = (acc_ref[:].astype(jnp.int32) == popc_ref[:]).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def packed_contains_matrix(sketch_packed, ref_packed, ref_popc, *,
                           interpret: bool = False):
    """(D, R) uint8 containment matrix from packed uint32 rows.

    sketch_packed: (D, W) packed dep sketches; ref_packed: (R, W) packed ref bit
    sets; ref_popc: (R,) int32 popcounts of each ref row.  D and R must be
    multiples of the 128-lane tile; W a power-of-two number of words (bits a
    power of two >= 32, as ops/sketch.py enforces).  `interpret=True` runs the
    kernel in the Pallas interpreter (CPU tests).
    """
    d, w = sketch_packed.shape
    r = ref_packed.shape[0]
    wk = min(w, WK_MAX)
    if d % TILE_D or r % TILE_R or w % wk:
        raise ValueError(f"shapes must be tile-aligned, got D={d} R={r} W={w}")
    grid = (d // TILE_D, r // TILE_R, w // wk)
    return pl.pallas_call(
        _contains_kernel,
        out_shape=jax.ShapeDtypeStruct((d, r), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_D, wk), lambda i, j, k: (i, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_R, wk), lambda i, j, k: (j, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_R), lambda i, j, k: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_D, TILE_R), lambda i, j, k: (i, j),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((TILE_D, TILE_R), jnp.float32)],
        # Renamed upstream (TPUCompilerParams -> CompilerParams); support both
        # spellings so the kernel loads on old and new jax alike.
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(sketch_packed, ref_packed, ref_popc.reshape(1, r))
