"""Pallas TPU kernels for the packed-bitset hot path.

The reference's compute-critical "native" surface is its forked-Guava Bloom
filter (SURVEY.md §2: exportBits/intersect + SpectralBloomFilter) — the bit
twiddling under every approximate strategy.  Here that surface is the packed
(rows × bits/32) uint32 sketch matrix (ops/sketch.py), and its hot op is the
containment matmul: "which hash-bit sets are fully contained in which sketch
rows", for all (dep, ref) pairs at once.

The jnp path (sketch.contains_matrix) unpacks both sides to full 0/1 planes in
HBM — a 32x write + read amplification of pure memory traffic — before the MXU
contraction.  The kernel below never materializes planes: each grid step DMAs a
packed (TILE, W) uint32 tile into VMEM, unpacks 4 words (128 bits) at a time
into bf16 registers, and feeds the MXU with (TILE, 128) @ (128, TILE) partial
contractions, accumulating in f32.  HBM traffic drops to the packed bytes.

Layout notes (see /opt/skills/guides/pallas_guide.md): last dim is 128 lanes;
the unpack builds each 128-lane group by broadcasting one packed word column
(TILE, 1) against a (1, 32) shift iota — no in-kernel reshapes or gathers, only
broadcasts and lane-dim concatenation, which Mosaic handles natively.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_D = 128
TILE_R = 128
_WORDS_PER_STEP = 4  # 4 uint32 words = 128 contraction lanes = one full MXU K


def _unpack4(ref, w0):
    """(TILE, 4 words) of a packed uint32 ref -> (TILE, 128) 0/1 bf16 planes."""
    shifts = jax.lax.broadcasted_iota(jnp.uint32, (1, 32), 1)
    groups = [
        ((ref[:, pl.ds(w0 + i, 1)] >> shifts) & jnp.uint32(1)).astype(jnp.bfloat16)
        for i in range(_WORDS_PER_STEP)
    ]
    return jnp.concatenate(groups, axis=1)


def _contains_kernel(s_ref, r_ref, popc_ref, out_ref):
    """One (TILE_D, TILE_R) tile of the containment matrix.

    s_ref: (TILE_D, W) packed dep sketches; r_ref: (TILE_R, W) packed ref bit
    sets; popc_ref: (1, TILE_R) per-ref set bit counts.  out[d, r] = 1 iff every
    set bit of ref r is set in sketch d, tested as <unpacked s, unpacked r> ==
    popcount(r) — the same MXU formulation as the jnp path, minus the HBM planes.
    """
    w = s_ref.shape[1]

    def body(k, acc):
        s_b = _unpack4(s_ref, k * _WORDS_PER_STEP)
        r_b = _unpack4(r_ref, k * _WORDS_PER_STEP)
        return acc + jax.lax.dot_general(
            s_b, r_b, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    acc = jax.lax.fori_loop(
        0, w // _WORDS_PER_STEP, body,
        jnp.zeros((s_ref.shape[0], r_ref.shape[0]), jnp.float32))
    out_ref[:] = (acc.astype(jnp.int32) == popc_ref[:]).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def packed_contains_matrix(sketch_packed, ref_packed, ref_popc, *,
                           interpret: bool = False):
    """(D, R) uint8 containment matrix from packed uint32 rows.

    sketch_packed: (D, W) packed dep sketches; ref_packed: (R, W) packed ref bit
    sets; ref_popc: (R,) int32 popcounts of each ref row.  D and R must be
    multiples of the 128-lane tile; W a multiple of 4.  `interpret=True` runs
    the kernel in the Pallas interpreter (CPU tests).
    """
    d, w = sketch_packed.shape
    r = ref_packed.shape[0]
    if d % TILE_D or r % TILE_R or w % _WORDS_PER_STEP:
        raise ValueError(f"shapes must be tile-aligned, got D={d} R={r} W={w}")
    grid = (d // TILE_D, r // TILE_R)
    return pl.pallas_call(
        _contains_kernel,
        out_shape=jax.ShapeDtypeStruct((d, r), jnp.uint8),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[
                pl.BlockSpec((TILE_D, w), lambda i, j: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((TILE_R, w), lambda i, j: (j, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, TILE_R), lambda i, j: (0, j),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((TILE_D, TILE_R), lambda i, j: (i, j),
                                   memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(sketch_packed, ref_packed, ref_popc.reshape(1, r))
