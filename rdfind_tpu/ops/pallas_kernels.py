"""Pallas TPU kernels for the packed-bitset hot path.

The reference's compute-critical "native" surface is its forked-Guava Bloom
filter (SURVEY.md §2: exportBits/intersect + SpectralBloomFilter) — the bit
twiddling under every approximate strategy.  Here that surface is the packed
(rows × bits/32) uint32 sketch matrix (ops/sketch.py), and its hot op is the
containment matmul: "which hash-bit sets are fully contained in which sketch
rows", for all (dep, ref) pairs at once.

The jnp path (sketch.contains_matrix) unpacks both sides to full 0/1 planes in
HBM — a 32x write + read amplification of pure memory traffic — before the MXU
contraction.  The kernel below never materializes planes: each grid step DMAs a
packed (TILE, WK) uint32 tile into VMEM, unpacks it in-register, and feeds the
MXU with a (TILE, WK*32) contraction, accumulating across word chunks in a VMEM
scratch.  HBM traffic drops to the packed bytes.

MXU-rate notes (the round-6 rework):

  * planes unpack to **int8 by default** (int32 accumulation — exact, counts
    <= bits): half the VMEM per unpacked operand and 2x the MXU rate of the
    bf16 fallback on int8-capable chips (v5e: 394 int8 TOPS vs 197 bf16
    TFLOPS).  `unpack_dtype="bf16"` keeps the old formulation for backends
    whose MXU has no int8 path — both modes are bit-exact vs the jnp planes
    path (counts are small integers either way).
  * **int4 nibble planes** (`unpack_dtype="int4"`, the round-7 rung via
    RDFIND_PLANE_BITS) halve the element again: WK widens to 512 words =
    16384 contraction lanes per K step, so each MXU pass covers twice
    int8's K-dim at the same VMEM budget.  Accumulation stays int32 (still
    exact); backends without native int4 elements run the same widened-WK
    grid with int8 elements (bit-identical — the emulation the CPU parity
    tests exercise, since XLA CPU rejects sub-byte conversion outright).
  * **int2 crumb planes** (`unpack_dtype="int2"`, RDFIND_PLANE_BITS=2, the
    round-12 rung) halve once more: WK 1024 words = 32768 contraction lanes
    per K step — four times int8's K-dim per MXU pass at the same VMEM
    budget.  The exactness argument is width-independent: planes are 0/1 in
    any element type and the accumulator stays int32, so a crumb holding
    {0, 1} loses nothing against a byte holding {0, 1}.  Backends without
    native int2 elements keep the quadrupled-WK grid with int8 elements,
    exactly like the int4 emulation.
  * the **dep-tile unpack is hoisted out of the ref-tile grid dimension**:
    the ref (j) dimension revisits the same dep tile nj times, so the shifted
    planes are computed once at j == 0 into a persistent VMEM scratch and
    re-read for every later j — the per-step VPU work drops to one ref-tile
    unpack.  The hoist is skipped (per-step unpack, as before) only when the
    full-width dep planes would not fit the scratch budget.
  * WK (words per K step) widens with the int8 VMEM savings, so each K-grid
    DMA moves a larger packed block and the MXU sees longer contractions.
  * the K grid dimension is marked "arbitrary" (sequential revisiting) in
    dimension_semantics, which is what lets Mosaic double-buffer the K-step
    operand DMAs against the matmul of the previous chunk; the ref-tile (j)
    dimension is also "arbitrary" because the hoisted scratch carries state
    across it.
  * **explicit K-step pipelining** (RDFIND_EMIT_PIPELINE, the round-12
    rung): where `pltpu.emit_pipeline` is available (probed — it asserts
    the TPU backend even under interpret=True, so the probe fails closed
    on CPU), the K grid dimension moves into a manual inner pipeline: the
    ref-side packed chunks stay HBM-resident (memory_space=ANY) and the
    pipeline's own double-buffered DMAs overlap each chunk's copy-in with
    the previous chunk's MXU pass, replacing Mosaic's implicit
    "arbitrary"-dimension buffering with an explicitly scheduled one.  The
    dep tile is fetched full-width once per (i, j) step and its planes
    hoisted exactly as in the grid variant, so outputs are bit-identical
    across emit on/off — the parity matrix asserts it.

Layout notes (see /opt/skills/guides/pallas_guide.md): Mosaic cannot slice the
lane dimension at non-128-aligned offsets, so the unpack avoids slicing
entirely: `pltpu.repeat(x, 32, axis=1)` tiles the packed words 32x along lanes
(np.tile semantics: lane j holds word j % WK), and the per-lane shift is
j // WK.  That yields planes in *bit-major* lane order — a fixed permutation of
the contraction dimension, harmless because both operands unpack identically
and the dot product is permutation-invariant.  Narrowing casts out of uint32
go through int32 (Mosaic has no direct uint32->bf16 lowering, r2 bench
failure; the int8 path keeps the same two-step shape for symmetry).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_D = 128
TILE_R = 128
# Words per K grid step, by unpack dtype: the unpacked operand tile is
# (TILE, WK*32) elements in VMEM, so int8's 1-byte planes afford twice the
# chunk of bf16 at the same budget (256 words = 8192 contraction lanes = 1 MB
# per int8 operand tile) — larger K-step DMAs, longer MXU contractions.
# int4 nibble planes (RDFIND_PLANE_BITS=4) halve the element again: 512
# words = 16384 contraction lanes per step, so each MXU pass covers twice
# int8's K-dim at the same VMEM budget; int2 crumb planes
# (RDFIND_PLANE_BITS=2) halve once more to 1024 words = 32768 lanes.
# Exactness is untouched — planes are 0/1 in every width and accumulation
# stays int32.
WK_MAX = {"int2": 1024, "int4": 512, "int8": 256, "bf16": 128}
# Bits per unpacked plane element, keyed by unpack dtype (the VMEM/hoist
# budget arithmetic; int4/int2 planes may fall back to int8 *elements* on
# backends without native sub-byte support — see _plane_elem — but keep
# their widened WK grid either way).
PLANE_ELEM_BITS = {"int2": 2, "int4": 4, "int8": 8, "bf16": 16}
# VMEM budget for the hoisted full-width dep planes (TILE_D x bits x elem
# bytes).  4 MB covers bits <= 65536 in int4 / 32768 in int8 / 16384 in
# bf16 and leaves the double-buffered operand tiles + accumulator well
# inside the ~16 MB core budget; wider sketches fall back to the per-step
# unpack.
HOIST_PLANE_BUDGET = 4 << 20


@functools.lru_cache(maxsize=1)
def _repeat_is_tile() -> bool:
    """Whether this jax's pltpu.repeat follows np.tile lane order (newer
    versions: lane j holds word j % WK) or np.repeat order (older: lane j
    holds word j // n).  The unpack's shift formula must match, or the
    planes stop being a permutation of the bits and the containment counts
    go silently wrong.  Probed once through the interpreter, which agrees
    with the Mosaic lowering within a jax version."""
    try:
        def k(x_ref, o_ref):
            o_ref[:] = pltpu.repeat(x_ref[:], 2, axis=1)

        # The first call can land inside an outer jit/pallas trace (the
        # kernel is traced lazily); escape it so the probe runs eagerly —
        # staged, its output would be a tracer and the comparison would
        # bogusly take the except path.
        with jax.ensure_compile_time_eval():
            out = pl.pallas_call(
                k, out_shape=jax.ShapeDtypeStruct((1, 4), jnp.int32),
                interpret=True)(jnp.arange(2, dtype=jnp.int32).reshape(1, 2))
            host = [int(v) for v in np.asarray(out)[0]]
        return host == [0, 1, 0, 1]
    except Exception:
        return True  # current upstream semantics


def _default_unpack_dtype() -> str:
    """The resolved kernel dtype: int4 nibble planes where the plane-bits
    policy engages, else int8 wherever the backend's int8 matmul path pays
    off (the cooc probes), bf16 elsewhere or when pinned via
    RDFIND_COOC_DTYPE — one policy for every containment contraction."""
    from . import cooc

    return cooc.resolved_kernel_dtype()


def _plane_elem(dtype: str) -> str:
    """Resolved element type the planes are actually stored/contracted in.

    "int4"/"int2" planes use native sub-byte jnp elements only where the
    backend's matching matmul lowers (cooc.int4_elements_native /
    int2_elements_native probes); elsewhere the sub-byte mode keeps its
    widened-WK grid but stores int8 elements — the arithmetic is identical
    (0/1 planes, int32 accumulation), so outputs are bit-identical and
    every mode stays differential-testable on CPU, whose XLA rejects
    sub-byte conversions outright.  The result is a STATIC jit key
    alongside unpack_dtype: a probe flip must retrace."""
    from . import cooc

    if dtype == "int4":
        return "int4" if cooc.int4_elements_native() else "int8"
    if dtype == "int2":
        return "int2" if cooc.int2_elements_native() else "int8"
    return dtype


_PLANE_JNP = {"bf16": jnp.bfloat16, "int8": jnp.int8}
if hasattr(jnp, "int4"):
    _PLANE_JNP["int4"] = jnp.int4
if hasattr(jnp, "int2"):
    _PLANE_JNP["int2"] = jnp.int2


def _repeat32(x):
    """The 32x lane repeat behind the unpack — module-level indirection so
    tests can substitute a jnp.tile / jnp.repeat emulation of either lane
    order and exercise both _repeat_is_tile branches on any jax version."""
    return pltpu.repeat(x, 32, axis=1)


def _unpack_tile(x, plane_dt, tile_order: bool):
    """(TILE, WK) packed uint32 -> (TILE, WK*32) 0/1 planes in `plane_dt`.

    Lane j of the result is bit (j // WK) of word (j % WK) under tile-order
    repeat, or bit (j % 32) of word (j // 32) under repeat-order — either is
    a fixed permutation of the bits, harmless because both operands unpack
    identically and the dot product is permutation-invariant.  Only
    full-tile ops: repeat, iota, shift, compare — no lane slicing (Mosaic
    requires lane-dim slice offsets to be 128-aligned, which word steps are
    not).
    """
    wk = x.shape[1]
    rep = _repeat32(x)
    lane = jax.lax.broadcasted_iota(jnp.uint32, rep.shape, 1)
    shifts = (jax.lax.div(lane, jnp.uint32(wk)) if tile_order
              else jax.lax.rem(lane, jnp.uint32(32)))
    bits = ((rep >> shifts) & jnp.uint32(1)).astype(jnp.int32)
    return bits.astype(plane_dt)


def _contains_kernel(s_ref, r_ref, popc_ref, out_ref, s_plane_ref, acc_ref, *,
                     nk: int, wk: int, plane_dt, tile_order: bool,
                     hoist: bool, acc_dt):
    """One (TILE_D, TILE_R) tile of the containment matrix.

    s_ref: (TILE_D, WK) packed dep sketches; r_ref: (TILE_R, WK) packed ref bit
    sets; popc_ref: (1, TILE_R) per-ref set bit counts.  out[d, r] = 1 iff every
    set bit of ref r is set in sketch d, tested as <unpacked s, unpacked r> ==
    popcount(r) — the same MXU formulation as the jnp path, minus the HBM
    planes.  The K grid dim accumulates word chunks into acc_ref; with `hoist`,
    s_plane_ref carries the full-width unpacked dep planes across the ref (j)
    dimension, filled once per (i, k) while j == 0.
    """
    k = pl.program_id(2)
    j = pl.program_id(1)
    wk32 = wk * 32

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    if hoist:
        # nk == 1 keeps the chunk offset static; otherwise wk32 is a
        # 128-multiple (wk == WK_MAX there), so the dynamic lane offset stays
        # Mosaic-aligned.
        chunk = (slice(0, wk32) if nk == 1
                 else pl.ds(k * wk32, wk32))

        @pl.when(j == 0)
        def _fill():
            s_plane_ref[:, chunk] = _unpack_tile(s_ref[:], plane_dt,
                                                 tile_order)

        s_b = s_plane_ref[:, chunk]
    else:
        s_b = _unpack_tile(s_ref[:], plane_dt, tile_order)
    r_b = _unpack_tile(r_ref[:], plane_dt, tile_order)
    acc_ref[:] += jax.lax.dot_general(
        s_b, r_b, (((1,), (1,)), ((), ())),
        preferred_element_type=acc_dt)

    @pl.when(k == nk - 1)
    def _finalize():
        out_ref[:] = (acc_ref[:].astype(jnp.int32) == popc_ref[:]).astype(jnp.uint8)


@functools.lru_cache(maxsize=1)
def emit_pipeline_supported() -> bool:
    """Whether pltpu.emit_pipeline actually traces AND runs here.

    hasattr alone is not a probe: the API exists on every recent jax but
    asserts the TPU backend at trace time even under interpret=True, so on
    the CPU proxy a hasattr gate would select a kernel that cannot compile.
    Instead a minimal two-step accumulation pipeline is run end to end
    (probe-before-assume, like _repeat_is_tile); any failure — missing
    API, backend assert, lowering error — falls back to the PR-6
    "arbitrary"-dimension K grid, which is bit-identical."""
    if not hasattr(pltpu, "emit_pipeline"):
        return False
    try:
        def kern(x_hbm, o_ref, acc_ref):
            acc_ref[:] = jnp.zeros_like(acc_ref)

            def body(x_ref):
                acc_ref[:] += x_ref[:]

            pltpu.emit_pipeline(
                body, grid=(2,),
                in_specs=[pl.BlockSpec((8, 128), lambda k: (k, 0))])(x_hbm)
            o_ref[:] = acc_ref[:]

        with jax.ensure_compile_time_eval():
            out = pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
                scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
            )(jnp.ones((16, 128), jnp.float32))
            return bool(np.asarray(out)[0, 0] == 2.0)
    except Exception:
        return False


def _contains_kernel_emit(s_ref, r_hbm, popc_ref, out_ref, s_plane_ref,
                          acc_ref, step_ref, *, nk: int, wk: int, plane_dt,
                          tile_order: bool, hoist: bool, acc_dt):
    """The emit-pipeline variant of _contains_kernel: outer grid (i, j)
    only; the K dimension runs as an explicit pltpu.emit_pipeline whose
    double-buffered DMAs stream the packed ref chunks out of HBM
    (memory_space=ANY) while the previous chunk's MXU pass runs.  The dep
    tile arrives full-width in VMEM once per (i, j) step; its planes are
    hoisted into scratch at j == 0 exactly as in the grid variant (chunked
    unpack — the uint32 repeat intermediate must stay one chunk wide).
    step_ref (SMEM) tracks the inner step because the pipeline body runs
    under its own grid env, where pl.program_id no longer names the outer
    axes."""
    j = pl.program_id(1)
    wk32 = wk * 32
    acc_ref[:] = jnp.zeros_like(acc_ref)
    step_ref[0] = 0

    if hoist:
        @pl.when(j == 0)
        def _fill():
            for kk in range(nk):  # static unroll: one chunk-wide unpack each
                s_plane_ref[:, kk * wk32:(kk + 1) * wk32] = _unpack_tile(
                    s_ref[:, kk * wk:(kk + 1) * wk], plane_dt, tile_order)

    def body(r_ref):
        k = step_ref[0]
        if hoist:
            # nk == 1 keeps the chunk offset static; otherwise wk32 is a
            # 128-multiple (wk == WK_MAX there), so the dynamic lane offset
            # stays Mosaic-aligned — same contract as the grid variant.
            chunk = (slice(0, wk32) if nk == 1 else pl.ds(k * wk32, wk32))
            s_b = s_plane_ref[:, chunk]
        else:
            pchunk = (slice(0, wk) if nk == 1 else pl.ds(k * wk, wk))
            s_b = _unpack_tile(s_ref[:, pchunk], plane_dt, tile_order)
        r_b = _unpack_tile(r_ref[:], plane_dt, tile_order)
        acc_ref[:] += jax.lax.dot_general(
            s_b, r_b, (((1,), (1,)), ((), ())),
            preferred_element_type=acc_dt)
        step_ref[0] = k + 1

    pltpu.emit_pipeline(
        body, grid=(nk,),
        in_specs=[pl.BlockSpec((popc_ref.shape[1], wk),
                               lambda k: (j, k))])(r_hbm)
    out_ref[:] = (acc_ref[:].astype(jnp.int32) == popc_ref[:]).astype(jnp.uint8)


def packed_contains_matrix(sketch_packed, ref_packed, ref_popc, *,
                           interpret: bool = False,
                           unpack_dtype: str | None = None,
                           emit_pipeline: bool | None = None):
    """(D, R) uint8 containment matrix from packed uint32 rows.

    sketch_packed: (D, W) packed dep sketches; ref_packed: (R, W) packed ref bit
    sets; ref_popc: (R,) int32 popcounts of each ref row.  D and R must be
    multiples of the 128-lane tile; W a power-of-two number of words (bits a
    power of two >= 32, as ops/sketch.py enforces).  `interpret=True` runs the
    kernel in the Pallas interpreter (CPU tests).  `unpack_dtype` selects the
    in-register plane type ("int8" wherever int8 matmul lowers — the default —
    else "bf16"); every mode is exact and bit-identical.  `emit_pipeline`
    selects the explicit K-step pipeline (default: the resolved
    RDFIND_EMIT_PIPELINE policy); where the probe says the API cannot run,
    the request silently degrades to the grid variant — same outputs.
    """
    if unpack_dtype is None:
        unpack_dtype = _default_unpack_dtype()
    if unpack_dtype not in WK_MAX:
        raise ValueError(f"unpack_dtype must be int2, int4, int8 or bf16, "
                         f"got {unpack_dtype!r}")
    if emit_pipeline is None:
        from . import cooc

        emit_pipeline = cooc.emit_pipeline_enabled()
    # The pltpu.repeat lane-order probe keys the jit cache, and so do the
    # resolved plane element type and the emit-pipeline resolution (PR-2's
    # static-key discipline extended to plane width and K-step schedule): a
    # monkeypatched or version-dependent flip must retrace the kernel, not
    # reuse the other mode's program.
    return _packed_contains_matrix(sketch_packed, ref_packed, ref_popc,
                                   interpret=interpret,
                                   unpack_dtype=unpack_dtype,
                                   plane_elem=_plane_elem(unpack_dtype),
                                   tile_order=_repeat_is_tile(),
                                   emit=bool(emit_pipeline)
                                   and emit_pipeline_supported())


@functools.partial(jax.jit, static_argnames=("interpret", "unpack_dtype",
                                             "plane_elem", "tile_order",
                                             "emit"))
def _packed_contains_matrix(sketch_packed, ref_packed, ref_popc, *,
                            interpret: bool, unpack_dtype: str,
                            plane_elem: str, tile_order: bool,
                            emit: bool = False):
    d, w = sketch_packed.shape
    r = ref_packed.shape[0]
    wk = min(w, WK_MAX[unpack_dtype])
    if d % TILE_D or r % TILE_R or w % wk:
        raise ValueError(f"shapes must be tile-aligned, got D={d} R={r} W={w}")
    nk = w // wk
    # Budget arithmetic follows the unpack *mode* (int4/int2 plan for
    # sub-byte VMEM even when elements emulate as int8 — the WK grid must
    # not depend on the emulation fallback or the two would compile
    # different K steps).
    elem_bits = PLANE_ELEM_BITS[unpack_dtype]
    plane_dt = _PLANE_JNP.get(plane_elem, jnp.int8)
    acc_dt = jnp.float32 if unpack_dtype == "bf16" else jnp.int32
    hoist = TILE_D * w * 32 * elem_bits // 8 <= HOIST_PLANE_BUDGET
    if emit:
        kernel = functools.partial(_contains_kernel_emit, nk=nk, wk=wk,
                                   plane_dt=plane_dt, tile_order=tile_order,
                                   hoist=hoist, acc_dt=acc_dt)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((d, r), jnp.uint8),
            grid=(d // TILE_D, r // TILE_R),
            in_specs=[
                # Dep tile full-width in VMEM (packed words are 4 bytes x W
                # <= 8 KB per row — far under the plane scratch itself);
                # ref side stays HBM-resident, chunks DMAed by the inner
                # pipeline.
                pl.BlockSpec((TILE_D, w), lambda i, j: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec((1, TILE_R), lambda i, j: (0, j),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((TILE_D, TILE_R), lambda i, j: (i, j),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((TILE_D, (w if hoist else wk) * 32), plane_dt),
                pltpu.VMEM((TILE_D, TILE_R), acc_dt),
                pltpu.SMEM((1,), jnp.int32),
            ],
            # j is "arbitrary": the hoisted dep planes carry state across
            # the ref tiles; the K dimension lives inside the kernel now.
            compiler_params=getattr(pltpu, "CompilerParams",
                                    getattr(pltpu, "TPUCompilerParams",
                                            None))(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=interpret,
        )(sketch_packed, ref_packed, ref_popc.reshape(1, r))
    grid = (d // TILE_D, r // TILE_R, nk)
    kernel = functools.partial(_contains_kernel, nk=nk, wk=wk,
                               plane_dt=plane_dt, tile_order=tile_order,
                               hoist=hoist, acc_dt=acc_dt)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((d, r), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_D, wk), lambda i, j, k: (i, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_R, wk), lambda i, j, k: (j, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_R), lambda i, j, k: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_D, TILE_R), lambda i, j, k: (i, j),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            # Hoisted dep planes (full width when hoisting, one chunk's worth
            # of scratch otherwise so the allocation stays tiny and unused).
            pltpu.VMEM((TILE_D, (w if hoist else wk) * 32), plane_dt),
            pltpu.VMEM((TILE_D, TILE_R), acc_dt),
        ],
        # Renamed upstream (TPUCompilerParams -> CompilerParams); support both
        # spellings so the kernel loads on old and new jax alike.  j and k are
        # "arbitrary": j carries the hoisted-scratch state sequentially, and
        # k's sequential revisiting is what Mosaic double-buffers the K-step
        # operand DMAs across.
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(sketch_packed, ref_packed, ref_popc.reshape(1, r))


# ---------------------------------------------------------------------------
# Fused verdict + minimality pre-filter kernel (ISSUE 6 rung 2): the dense
# CIND sweep without materializing the cooc count matrix in HBM.
#
# The materialized path (cooc.cooc_cind_tile) computes a (tile x c_pad)
# int32 count matrix as one XLA dot — which lands in HBM between the dot
# and the elementwise verdict/mask ops — then compares, masks, and packs.
# Here each (128 x 128) count block only ever exists in a VMEM scratch
# accumulator; the epilogue applies the full verdict in-register (CIND test,
# support filter, diagonal, and the trivially-implied-pair rule of
# data/Condition.scala:35-43 — the same masks _stage_merge applies) and
# emits a uint8 verdict tile (4x smaller than the counts; packing to 32-bit
# words happens in the enclosing jit — Mosaic exposes no lane-group
# reduction to pack in-kernel) plus the per-dep referenced-set popcount the
# minimality/extraction stages size with.
#
# K-step streaming (rungs 3+4): the line dimension walks a scalar-prefetched
# block-id schedule, so all-zero (dep-tile x line-block) pairs — per-block
# membership popcounts, the join-line skew record — are never fetched, and
# the j/k grid dims are "arbitrary" so Mosaic double-buffers the K-step
# operand DMAs against the previous block's matmul.  Of the two explicit
# K-step mechanisms the roofline plan names, this kernel rides the
# scalar-prefetched grid (its K schedule is data-dependent — the block-id
# list IS the prefetched scalar, already an explicitly scheduled,
# double-buffered K loop), while the containment kernel
# above carries the pltpu.emit_pipeline variant (RDFIND_EMIT_PIPELINE;
# probed, not assumed, like the pltpu.repeat shim).  Padded schedule
# entries fetch block 0 and are compute-guarded by the prefetched
# real-block count.
# ---------------------------------------------------------------------------

CIND_BLOCK_D = 128
CIND_BLOCK_R = 128


def scalar_prefetch_available() -> bool:
    """Whether this jax ships the scalar-prefetch grid the fused kernel's
    K-step schedule rides (probe-before-assume, like the pltpu.repeat
    shim).  Absent it, the fused path stays off and the materialized
    sweep runs — no hard dependency on the newer API."""
    return hasattr(pltpu, "PrefetchScalarGridSpec")


def _fused_cind_kernel(bids_ref, nreal_ref, md_ref, mr_ref, sup_ref, ok_ref,
                       gid_ref, dcode_ref, dv1_ref, dv2_ref, ridx_ref,
                       rcode_ref, rv1_ref, verdict_ref, popc_ref, acc_ref, *,
                       nk: int, acc_dt):
    from .. import conditions as cc

    j = pl.program_id(1)
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(k < nreal_ref[0])
    def _accum():
        acc_ref[:] += jax.lax.dot_general(
            md_ref[:], mr_ref[:], (((0,), (0,)), ((), ())),
            preferred_element_type=acc_dt)

    @pl.when(k == nk - 1)
    def _finalize():
        # f32 accumulation (bf16 membership) is exact below 2^24 lines —
        # the same bound the dense plan enforces — so the cast is exact.
        cooc = acc_ref[:].astype(jnp.int32)
        sup = sup_ref[:]                      # (BLOCK_D, 1) broadcasts
        is_cind = (cooc == sup) & (ok_ref[:] != 0)
        is_cind &= gid_ref[:] != ridx_ref[:]  # no self-pairs
        d_code = dcode_ref[:]
        r_code = rcode_ref[:]
        implied = cc.is_subcode(r_code, d_code) & jnp.where(
            cc.first_subcapture(d_code) == r_code,
            rv1_ref[:] == dv1_ref[:], rv1_ref[:] == dv2_ref[:])
        v = is_cind & ~implied
        verdict_ref[:] = v.astype(jnp.uint8)
        row = jnp.sum(v.astype(jnp.int32), axis=1, keepdims=True)

        @pl.when(j == 0)
        def _set():
            popc_ref[:] = row

        @pl.when(j != 0)
        def _add():
            popc_ref[:] += row


def fused_cind_blocks(m_dep, m, sup_col, ok_col, gid_col, dcode_col, dv1_col,
                      dv2_col, ridx_row, rcode_row, rv1_row, block_ids,
                      n_real, *, ref_lo: int, ref_chunk: int,
                      interpret: bool = False):
    """(tile x ref_chunk) fused CIND verdict + (tile, 1) per-dep popcount.

    m_dep: (l_pad, tile) dep-slice of the membership matrix; m: (l_pad,
    c_pad) the full matrix (the ref side reads blocks at a static `ref_lo`
    column offset through the index map — no slice copy).  The *_col
    operands are (tile, 1) per-dep columns (support, support>=min_support,
    global capture id, code, v1, v2); the *_row operands (1, c_pad)
    per-ref rows.  block_ids/n_real: the scalar-prefetched K schedule —
    int32 (nk,) line-block ids (entries past n_real are padding) and the
    (1,) real count.
    """
    l_pad, tile = m_dep.shape
    c_pad = m.shape[1]
    nk = block_ids.shape[0]
    kl = _fused_kl(l_pad)
    if tile % CIND_BLOCK_D or ref_chunk % CIND_BLOCK_R or l_pad % kl:
        raise ValueError(f"fused tile not block-aligned: tile={tile} "
                         f"ref_chunk={ref_chunk} l_pad={l_pad}")
    acc_dt = jnp.float32 if m.dtype == jnp.bfloat16 else jnp.int32
    grid = (tile // CIND_BLOCK_D, ref_chunk // CIND_BLOCK_R, nk)
    rb = ref_lo // CIND_BLOCK_R
    kernel = functools.partial(_fused_cind_kernel, nk=nk, acc_dt=acc_dt)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((kl, CIND_BLOCK_D),
                         lambda i, j, k, b, n: (b[k], i)),
            pl.BlockSpec((kl, CIND_BLOCK_R),
                         lambda i, j, k, b, n: (b[k], rb + j)),
            pl.BlockSpec((CIND_BLOCK_D, 1), lambda i, j, k, b, n: (i, 0)),
            pl.BlockSpec((CIND_BLOCK_D, 1), lambda i, j, k, b, n: (i, 0)),
            pl.BlockSpec((CIND_BLOCK_D, 1), lambda i, j, k, b, n: (i, 0)),
            pl.BlockSpec((CIND_BLOCK_D, 1), lambda i, j, k, b, n: (i, 0)),
            pl.BlockSpec((CIND_BLOCK_D, 1), lambda i, j, k, b, n: (i, 0)),
            pl.BlockSpec((CIND_BLOCK_D, 1), lambda i, j, k, b, n: (i, 0)),
            pl.BlockSpec((1, CIND_BLOCK_R), lambda i, j, k, b, n: (0, rb + j)),
            pl.BlockSpec((1, CIND_BLOCK_R), lambda i, j, k, b, n: (0, rb + j)),
            pl.BlockSpec((1, CIND_BLOCK_R), lambda i, j, k, b, n: (0, rb + j)),
        ],
        out_specs=[
            pl.BlockSpec((CIND_BLOCK_D, CIND_BLOCK_R),
                         lambda i, j, k, b, n: (i, j)),
            pl.BlockSpec((CIND_BLOCK_D, 1), lambda i, j, k, b, n: (i, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((CIND_BLOCK_D, CIND_BLOCK_R), acc_dt)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((tile, ref_chunk), jnp.uint8),
                   jax.ShapeDtypeStruct((tile, 1), jnp.int32)],
        # i is parallel; j carries the popc accumulation and k the VMEM
        # count accumulator, both sequential ("arbitrary") — which is also
        # what lets Mosaic double-buffer the K-step operand DMAs.
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(block_ids, n_real, m_dep, m, sup_col, ok_col, gid_col, dcode_col,
      dv1_col, dv2_col, ridx_row, rcode_row, rv1_row)


def _fused_kl(l_pad: int) -> int:
    """K-step rows per block of the fused sweep — delegated to the plan's
    line-block granule so the kernel and the skip schedule agree."""
    from . import cooc

    return cooc.line_block_for(l_pad)
