"""Bitset sketches: the TPU-native form of the reference's extended Bloom filters.

The reference's approximate strategies lean on a forked Guava library — BloomFilter
with exportBits/wrap/intersect (CreateAllHalfApproximateCindCandidates.scala:110-116,
IntersectHalfApproximateCindCandidates.scala:40-44) and SpectralBloomFilter, a
counting filter (ExtractBalancedHalfApproximateUnaryUnaryOverlapCandidates.scala:
24-37).  Here those become fixed-width **bitset rows in HBM**:

  * a join line's capture set   -> one `bits`-wide Bloom row (scatter-OR build);
  * a dependent's refset sketch -> bitwise AND of the Bloom rows of every join line
    containing the dependent (segment-AND) — a conservative superset of the exact
    refset, because AND of Blooms ⊇ Bloom of the intersection;
  * candidate generation        -> "are all k hash bits of capture r set in
    sketch[d]?" for every (d, r) at once, phrased as a bf16 matmul on the MXU:
    (deps × bits) @ (bits × refs) == popcount(bits of r);
  * the spectral filter         -> a count-min sketch (saturating scatter-add,
    min-of-k query).

Everything is fixed-shape and jittable; rows are packed 32 bits/uint32 lane for
storage (`bits/32` words) and unpacked to 0/1 planes only inside a stage, where
elementwise min/max on {0,1} plays bitwise AND/OR.  A Pallas kernel can later run
the packed AND directly; the planes layout is already the MXU-friendly one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import hashing

DEFAULT_BITS = 2048
DEFAULT_HASHES = 4

# Row budgets for host-side chunking of the build stages (see models/approximate).
BUILD_ROW_BUDGET = 1 << 18


def bit_positions(ids, *, bits: int, num_hashes: int):
    """(n, k) int32 hash-bit positions in [0, bits) for dense int32 ids.

    The double-hashing scheme (h1 + i*h2, as in Guava's BloomFilterStrategies):
    two mixed 32-bit hashes generate all k positions.

    `bits` must be a power of two >= 32: positions are masked with `bits - 1`
    (a non-pow2 width would silently dead-zone part of the filter) and rows are
    packed 32 bits per uint32 lane.
    """
    if bits < 32 or bits & (bits - 1):
        raise ValueError(f"sketch bits must be a power of two >= 32, got {bits}")
    h1 = hashing.hash_cols([ids], seed=1)
    h2 = hashing.hash_cols([ids], seed=2) | jnp.uint32(1)  # odd => full period
    i = jnp.arange(num_hashes, dtype=jnp.uint32)
    pos = h1[:, None] + i[None, :] * h2[:, None]
    return (pos & jnp.uint32(bits - 1)).astype(jnp.int32)


def pack_planes(planes):
    """(m, bits) 0/1 uint8 planes -> (m, bits//32) uint32 packed rows."""
    m, bits = planes.shape
    lanes = planes.reshape(m, bits // 32, 32).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return (lanes * weights[None, None, :]).sum(axis=2, dtype=jnp.uint32)


def unpack_planes(packed):
    """(m, W) uint32 packed rows -> (m, 32*W) 0/1 uint8 planes."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & jnp.uint32(1)
    return bits.reshape(packed.shape[0], packed.shape[1] * 32).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("num_lines", "bits", "num_hashes"))
def build_line_blooms(line_gid, cap_id, valid, *, num_lines: int, bits: int,
                      num_hashes: int):
    """Packed Bloom row per join line from (line, capture) membership rows.

    line_gid: dense line id per row; cap_id: capture id per row.  Invalid rows are
    dropped.  Returns (num_lines, bits//32) uint32.
    """
    pos = bit_positions(cap_id, bits=bits, num_hashes=num_hashes)
    li = jnp.where(valid, line_gid, num_lines)[:, None]
    planes = jnp.zeros((num_lines, bits), jnp.uint8)
    planes = planes.at[li, pos].max(jnp.uint8(1), mode="drop")
    return pack_planes(planes)


@functools.partial(jax.jit, static_argnames=("num_caps", "bits"))
def intersect_dep_sketches(cap_id, line_bloom_rows, valid, *, num_caps: int,
                           bits: int):
    """Per-dependent refset sketch: AND of the line Blooms the dependent occurs in.

    cap_id: capture id per row; line_bloom_rows: (n_rows, W) packed Bloom of each
    row's line.  Returns (num_caps, W) uint32; captures with no valid rows keep the
    all-ones sketch (empty AND), which callers must mask by support anyway.
    """
    planes = unpack_planes(line_bloom_rows)
    ci = jnp.where(valid, cap_id, num_caps)
    acc = jnp.ones((num_caps, bits), jnp.uint8)
    acc = acc.at[ci].min(planes, mode="drop")
    return pack_planes(acc)


@jax.jit
def intersect_dep_sketches_acc(acc, cap_id, line_bloom_rows, valid):
    """AND-accumulate one chunk's per-dependent sketches into `acc`.

    acc: (num_caps, W) packed sketches resident on device.  Equivalent to
    `acc & intersect_dep_sketches(...)` fused into one program with no host
    round trip — the r4 build pulled every chunk's partial sketch matrix to
    host and ANDed in numpy, which was strategy 2's first measured bottleneck
    (the AND of Blooms itself is the reference's BloomFilter.intersect,
    IntersectHalfApproximateCindCandidates.scala:40-44).
    """
    num_caps = acc.shape[0]
    planes = unpack_planes(line_bloom_rows)
    ci = jnp.where(valid, cap_id, num_caps)
    accp = unpack_planes(acc)
    accp = accp.at[ci].min(planes, mode="drop")
    return pack_planes(accp)


@functools.partial(jax.jit, static_argnames=("bits", "num_hashes"))
def pack_ref_bits(ref_ids, *, bits: int, num_hashes: int):
    """Packed (R, bits//32) uint32 bit sets of each ref id's k hash positions,
    plus (R,) int32 popcounts — the ref-side operand of the packed kernel."""
    r = ref_ids.shape[0]
    pos = bit_positions(ref_ids, bits=bits, num_hashes=num_hashes)  # (R, k)
    word, bit = pos >> 5, (pos & 31).astype(jnp.uint32)
    rows = jnp.zeros((r, bits // 32), jnp.uint32)
    ar = jnp.arange(r)
    for i in range(pos.shape[1]):  # k is tiny; sequential read-OR-write per hash
        prev = rows[ar, word[:, i]]
        rows = rows.at[ar, word[:, i]].set(prev | (jnp.uint32(1) << bit[:, i]))
    popc = jax.lax.population_count(rows).sum(axis=1).astype(jnp.int32)
    return rows, popc


def _pallas_backend_default() -> str:
    import os
    env = os.environ.get("RDFIND_PALLAS")
    if env is not None:
        return "jnp" if env.lower() in ("0", "false", "no") else "pallas"
    return "pallas" if jax.default_backend() == "tpu" else "jnp"


def pallas_eligible(bits: int, backend: str | None = None) -> bool:
    """Whether contains_matrix will dispatch to the Pallas kernel — the single
    eligibility rule, shared with callers that pre-pack the ref side."""
    if backend is None:
        backend = _pallas_backend_default()
    return backend == "pallas" and bits % 128 == 0


def _contract_dtype() -> str:
    """Element type of the jnp planes contraction: the resolved cooc dtype
    (int8 by default — int32 accumulation, exact; bf16 where int8 matmul
    does not lower).  Lazy import: cooc owns the probe and the env knob."""
    from . import cooc

    return cooc.resolved_cooc_dtype()


def _kernel_dtype() -> str:
    """Unpack dtype of the packed Pallas kernel: narrows to int4 nibble or
    int2 crumb planes under the plane-bits policy (RDFIND_PLANE_BITS) —
    each MXU pass then covers 2x/4x the K-dim — while the jnp fallback
    keeps the plain cooc dtype (XLA has no portable sub-byte contraction).
    All modes exact."""
    from . import cooc

    return cooc.resolved_kernel_dtype()


def contains_matrix(sketch_tile, ref_ids, ref_valid, *, bits: int,
                    num_hashes: int, backend: str | None = None,
                    interpret: bool = False, ref_pack=None):
    """(deps_tile × refs_tile) membership test on the MXU.

    sketch_tile: (D, W) packed dep sketches; ref_ids: (R,) capture ids.  Returns
    bool (D, R): True where every hash bit of ref r is set in sketch d — the
    candidate matrix of the approximate strategies.  The contraction runs in
    the resolved cooc dtype: int8 with int32 accumulation by default (exact —
    counts <= bits), bf16 with f32 accumulation as the fallback (counts <=
    num_hashes, exactly representable).

    backend: "pallas" (packed fused kernel, default on TPU — see
    ops/pallas_kernels.py) or "jnp" (unpacked-planes formulation, default
    elsewhere); `interpret` runs the Pallas kernel in interpreter mode (CPU
    tests).  `ref_pack` optionally supplies a precomputed pack_ref_bits result
    so callers looping over dep tiles pack the shared ref side once.
    """
    if pallas_eligible(bits, backend):
        from . import pallas_kernels

        d = sketch_tile.shape[0]
        r = ref_ids.shape[0]
        dp = -d % pallas_kernels.TILE_D
        rp = -r % pallas_kernels.TILE_R
        ref_packed, popc = (ref_pack if ref_pack is not None else
                            pack_ref_bits(ref_ids, bits=bits,
                                          num_hashes=num_hashes))
        if dp:
            sketch_tile = jnp.pad(sketch_tile, ((0, dp), (0, 0)))
        if rp:
            ref_packed = jnp.pad(ref_packed, ((0, rp), (0, 0)))
            # Padded refs get popc 0 while their row is empty => hits==popc
            # would hold; pin popc to an unreachable value instead.
            popc = jnp.pad(popc, (0, rp), constant_values=jnp.int32(-1))
        out = pallas_kernels.packed_contains_matrix(
            sketch_tile, ref_packed, popc, interpret=interpret,
            unpack_dtype=_kernel_dtype())
        return (out[:d, :r] == 1) & ref_valid[None, :]
    return _contains_matrix_jnp(sketch_tile, ref_ids, ref_valid, bits=bits,
                                num_hashes=num_hashes,
                                contract_dtype=_contract_dtype())


@functools.partial(jax.jit,
                   static_argnames=("bits", "num_hashes", "contract_dtype"))
def _contains_matrix_jnp(sketch_tile, ref_ids, ref_valid, *, bits: int,
                         num_hashes: int, contract_dtype: str = "bf16"):
    planes = unpack_planes(sketch_tile)  # (D, bits)
    r = ref_ids.shape[0]
    pos = bit_positions(ref_ids, bits=bits, num_hashes=num_hashes)  # (R, k)
    ref_planes = jnp.zeros((r, bits), jnp.uint8)
    ref_planes = ref_planes.at[jnp.arange(r)[:, None], pos].max(jnp.uint8(1))
    popc = ref_planes.sum(axis=1, dtype=jnp.int32)  # <= k (hash collisions)
    # contract_dtype is a STATIC jit key (the 0/1 planes' aval is uint8
    # either way): a dtype flip must retrace, not reuse the other program.
    dt = jnp.int8 if contract_dtype == "int8" else jnp.bfloat16
    acc = jnp.int32 if contract_dtype == "int8" else jnp.float32
    hits = jax.lax.dot_general(
        planes.astype(dt), ref_planes.astype(dt),
        (((1,), (1,)), ((), ())), preferred_element_type=acc)
    return (hits.astype(jnp.int32) == popc[None, :]) & ref_valid[None, :]


# ---------------------------------------------------------------------------
# Spectral filter analog: count-min sketch (saturating counts, min-of-k query).
# ---------------------------------------------------------------------------

MAX_COUNT_MIN_CAP = (1 << 16) - 1
_CM_CHUNK = 1 << 14


@functools.partial(jax.jit, static_argnames=("bits", "num_hashes", "cap"))
def count_min_add(keys, counts, valid, *, bits: int, num_hashes: int,
                  cap: int = MAX_COUNT_MIN_CAP):
    """Build a count-min sketch row: (bits,) int32 counters saturating at `cap`.

    The reference's SpectralBloomFilter (MultiunionHalfApproximateOverlap
    Candidates.scala:40-47) uses small fixed-width counters that saturate by
    design; `cap` (<= 2^16-1) is that width.  Each counter ends at exactly
    min(true_sum, cap): contributions are clipped to cap and accumulated in
    chunks of 2^14 rows with a clamp between chunks, so partial sums stay
    below 2^30 and int32 never wraps (x64 is disabled in this stack, so an
    int64 accumulator would silently truncate).
    """
    if not 0 < cap <= MAX_COUNT_MIN_CAP:
        raise ValueError(f"cap must be in (0, {MAX_COUNT_MIN_CAP}]")
    pos = bit_positions(keys, bits=bits, num_hashes=num_hashes)
    c = jnp.clip(jnp.where(valid, counts, 0), 0, cap).astype(jnp.int32)
    n = keys.shape[0]
    n_chunks = max(1, -(-n // _CM_CHUNK))
    padded = n_chunks * _CM_CHUNK
    pos = jnp.pad(pos, ((0, padded - n), (0, 0)))
    c = jnp.pad(c, (0, padded - n))

    def body(table, xs):
        p, cc = xs
        inc = jnp.zeros(bits, jnp.int32).at[p].add(cc[:, None])
        return jnp.minimum(table + inc, cap), None

    table, _ = jax.lax.scan(
        body, jnp.zeros(bits, jnp.int32),
        (pos.reshape(n_chunks, _CM_CHUNK, -1), c.reshape(n_chunks, _CM_CHUNK)))
    return table


def count_min_partial(keys, counts, valid, *, bits: int, num_hashes: int,
                      cap: int = MAX_COUNT_MIN_CAP, table=None):
    """One shard's partial count-min table, optionally folded into `table`.

    The shard-clean build contract: `bit_positions` is row-pure, so rows
    hash to the same counters no matter which device (or which dep-slice
    pass) holds them, and a partial table built with the same STATIC
    (bits, num_hashes, cap) is summable with any other — sum-then-cap over
    per-device partials is bit-identical to one `count_min_add` over the
    concatenated rows.  (The per-row clip at `cap` commutes with sharding
    because it is per-row, not per-shard-total.)

    Saturation contract (the all-reduce correctness lemma): for
    non-negative partial sums s_i,

        min(sum_i min(s_i, cap), cap) == min(sum_i s_i, cap)

    — if every s_i <= cap the inner min is the identity; otherwise some
    s_i > cap forces both sides to cap.  The lemma nests, so saturating
    after EVERY reduction level — the per-chunk clamp inside
    `count_min_add`'s scan, the per-pass fold here, the intra-host psum
    and the inter-host psum of `exchange.sketch_allreduce`, and the host
    `merge_count_min` — yields the same bits as one global sum-then-cap,
    while bounding every wire operand at `cap` (<= 2^16-1), so the int32
    psum cannot wrap below 2^15 participants per level.

    `table=None` returns this shard's partial; otherwise the partial is
    folded into the running table with the same saturating rule (the
    per-pass accumulation of the sharded two-round's round 1).
    """
    part = count_min_add(keys, counts, valid, bits=bits,
                         num_hashes=num_hashes, cap=cap)
    if table is None:
        return part
    # Both operands are <= cap <= 2^16-1, so the int32 sum cannot wrap.
    return jnp.minimum(table + part, cap)


@functools.partial(jax.jit, static_argnames=("bits", "num_hashes"))
def count_min_query(table, keys, *, bits: int, num_hashes: int):
    """Upper bound on each key's count: min over its k counters (getCount analog)."""
    pos = bit_positions(keys, bits=bits, num_hashes=num_hashes)
    return table[pos].min(axis=1)


def kernel_selfcheck(n_rows: int = 1024, n_bits: int = 4096,
                     backend: str | None = None, num_hashes: int = DEFAULT_HASHES,
                     repeats: int = 5) -> dict:
    """Bit-parity + timing of the Pallas packed kernel vs the jnp planes path.

    On TPU both paths run natively and the returned dict includes the speedup;
    on CPU the Pallas kernel runs in interpreter mode (parity only, timing of
    the interpreter would be meaningless).  Used by bench.py to report
    `pallas_vs_jnp` (VERDICT r1: the kernel had never been validated on
    hardware).
    """
    import time as _time

    if backend is None:
        backend = jax.default_backend()
    on_tpu = backend == "tpu"

    key = np.random.default_rng(11)
    dep_ids = jnp.asarray(key.integers(0, 1 << 30, n_rows, dtype=np.int32))
    ref_ids = jnp.asarray(key.integers(0, 1 << 30, n_rows, dtype=np.int32))
    ref_valid = jnp.ones(n_rows, bool)
    # Dep sketches: Bloom rows of random capture sets (one line per dep).
    line_gid = jnp.arange(n_rows, dtype=jnp.int32)
    sketches = build_line_blooms(line_gid, dep_ids, jnp.ones(n_rows, bool),
                                 num_lines=n_rows, bits=n_bits,
                                 num_hashes=num_hashes)

    def run(be, interpret=False, salt=0):
        out = contains_matrix(sketches, ref_ids + salt, ref_valid, bits=n_bits,
                              num_hashes=num_hashes, backend=be,
                              interpret=interpret)
        return jax.block_until_ready(out)

    out_jnp = run("jnp")
    out_pallas = run("pallas", interpret=not on_tpu)
    parity = bool(jnp.array_equal(out_jnp, out_pallas))

    from . import cooc
    result = {"parity": parity, "n_rows": n_rows, "bits": n_bits,
              "backend": backend,
              # The resolved kernel mode this selfcheck actually ran — the
              # provenance the bench kernel-mode rows and tpu_watch capture
              # key on (one row per knob set is meaningless without it).
              "kernel_dtype": _kernel_dtype(),
              "plane_bits": cooc.resolved_plane_bits(),
              "emit_pipeline": cooc.emit_pipeline_enabled()}
    # Content hash of the kernel output: lets bench rows taken under
    # DIFFERENT knob sets (plane bits, emit_pipeline) assert bit-identity
    # across rows, not just within-row jnp-vs-pallas parity.
    import hashlib
    result["out_hash"] = hashlib.sha1(
        np.asarray(out_pallas).tobytes()).hexdigest()[:16]
    # HBM traffic model of the packed kernel (ops/pallas_kernels.py grid):
    # each packed operand tile is re-read once per opposite-side tile, plus
    # the uint8 output write — the measured-bandwidth denominator for the
    # roofline (VERDICT r4 item 7: is 0.27% dense-peak actually BW-bound?).
    w = n_bits // 32
    from . import pallas_kernels as pk
    # Padded dims: contains_matrix pads both operands to tile multiples, so
    # real traffic scales with the padded shapes.
    d_pad = -(-n_rows // pk.TILE_D) * pk.TILE_D
    r_pad = -(-n_rows // pk.TILE_R) * pk.TILE_R
    result["hbm_bytes_model"] = int(
        (r_pad // pk.TILE_R) * d_pad * w * 4        # dep side re-reads
        + (d_pad // pk.TILE_D) * r_pad * w * 4      # ref side re-reads
        + d_pad * r_pad)                            # uint8 output
    if on_tpu:
        # Timing methodology: each repeat uses a *different* input (salted ids)
        # and the loop is drained by one scalar readback at the end — identical
        # repeated dispatches get streamlined by the runtime and report
        # physically-impossible per-call times (r2's 0.979x "speedup" artifact).
        for name, be in (("jnp_ms", "jnp"), ("pallas_ms", "pallas")):
            int(run(be, salt=-1).sum())  # compile, incl. the drain sum/add ops
            t0 = _time.perf_counter()
            acc = None
            for i in range(repeats):
                out = contains_matrix(sketches, ref_ids + (i + 1), ref_valid,
                                      bits=n_bits, num_hashes=num_hashes,
                                      backend=be)
                s = out.sum()
                acc = s if acc is None else acc + s
            int(acc)  # forces the whole chain to finish
            result[name] = round((_time.perf_counter() - t0) / repeats * 1e3, 3)
        result["speedup"] = round(result["jnp_ms"] / result["pallas_ms"], 3)
        # Kernel-only bandwidth: refs pre-packed outside the timed loop (the
        # end-to-end pallas_ms above keeps packing for a fair jnp speedup
        # comparison) and the drain's n^2 uint8 read added to the model, so
        # pallas_gbps reflects the kernel's real HBM rate.
        packs = [pack_ref_bits(ref_ids + (i + 1), bits=n_bits,
                               num_hashes=num_hashes) for i in range(repeats)]
        jax.block_until_ready(packs)
        # Warm with a DISTINCT pack: a warm dispatch identical to timed
        # iteration 0 would let the runtime streamline it (the r2 artifact
        # the salted loop above exists to avoid).
        warm_pack = pack_ref_bits(ref_ids - 1, bits=n_bits,
                                  num_hashes=num_hashes)
        int(contains_matrix(sketches, ref_ids - 1, ref_valid, bits=n_bits,
                            num_hashes=num_hashes, backend="pallas",
                            ref_pack=warm_pack).sum())
        t0 = _time.perf_counter()
        acc = None
        for i in range(repeats):
            out = contains_matrix(sketches, ref_ids + (i + 1), ref_valid,
                                  bits=n_bits, num_hashes=num_hashes,
                                  backend="pallas", ref_pack=packs[i])
            s = out.sum()
            acc = s if acc is None else acc + s
        int(acc)
        kernel_ms = (_time.perf_counter() - t0) / repeats * 1e3
        result["pallas_kernel_ms"] = round(kernel_ms, 3)
        result["pallas_gbps"] = round(
            (result["hbm_bytes_model"] + d_pad * r_pad)
            / (kernel_ms / 1e3) / 1e9, 1)
    return result


def merge_count_min(tables, cap: int = MAX_COUNT_MIN_CAP):
    """Sum of count-min tables (the combiner-tree merge), saturating.

    Host reference for the device-side saturating reduction
    (`exchange.sketch_allreduce`): this computes the exact int64 sum first
    and caps ONCE at the end, while the device path caps after every psum
    level — `count_min_partial`'s saturation lemma proves the two agree bit
    for bit whenever every input table is itself <= cap (which
    `count_min_add` guarantees).  Pinned by the differential test in
    tests/test_sketch_saturation.py, at and past the cap.
    """
    acc = np.zeros_like(np.asarray(tables[0]), dtype=np.int64)
    for t in tables:
        acc += np.asarray(t, np.int64)
    return np.minimum(acc, cap).astype(np.int32)
