"""Device primitives: segment algebra, join-candidate emission, pair generation,
bitset sketches.  Everything is int32 struct-of-arrays; nothing here touches strings.
"""
