"""Join-candidate emission: triples -> (join_value, capture) rows, fully vectorized.

Replaces CreateJoinPartners (operators/CreateJoinPartners.scala:86-147).  Per triple
and enabled projection field there are up to 3 captures sharing the projected value as
join value: two unary (condition on one other field) and one binary (condition on both,
values in ascending field-bit order).

Two deliberate divergences from the reference's emission, both output-neutral:

* The reference suppresses one unary partner when the binary partner is emitted and
  recreates it by splitting binary captures at the consumer
  (CreateDependencyCandidates.scala:90-105).  Emitting both unaries up front + the
  downstream dedupe produces identical join-line capture sets with no consumer-side
  splitting — on TPU a static 9-way emission pattern beats data-dependent branching.

* Frequency pruning uses exact counts (ops/frequency.py) instead of Bloom filters, so
  it prunes a superset of what the reference's filters prune; both are conservative.

Fixed-shape and jittable: output rows carry a validity mask instead of being
compacted; the projection set is a static (compile-time) argument.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from .. import conditions as cc
from .frequency import _FIELD_PAIRS, TripleFrequency

_FIELD_BITS = (cc.SUBJECT, cc.PREDICATE, cc.OBJECT)
_PAIR_INDEX = {pair: k for k, pair in enumerate(_FIELD_PAIRS)}
NO_VALUE = -1


@dataclasses.dataclass
class JoinCandidates:
    """Columnar join candidates with validity mask (fixed shape: 3 rows per triple
    per enabled projection)."""

    join_val: jnp.ndarray  # (C,) int32
    code: jnp.ndarray  # (C,) int32 capture code
    v1: jnp.ndarray  # (C,) int32
    v2: jnp.ndarray  # (C,) int32 (NO_VALUE for unary captures)
    valid: jnp.ndarray  # (C,) bool


def emit_join_candidates(triples, freq: TripleFrequency,
                         projections: str = "spo") -> JoinCandidates:
    """Emit all join candidates for an (N, 3) int32 triple table.

    Rows whose condition fails the frequency filter are emitted with valid=False.
    """
    n = triples.shape[0]
    parts = []  # (join_val, code_scalar, v1, v2, mask)
    for proj_char, proj_bit in zip("spo", _FIELD_BITS):
        if proj_char not in projections:
            continue
        pi = cc.FIELD_INDEX[proj_bit]
        a, b = [i for i in range(3) if i != pi]
        bit_a, bit_b = _FIELD_BITS[a], _FIELD_BITS[b]
        join_val = triples[:, pi]
        ok_a, ok_b = freq.unary_ok[:, a], freq.unary_ok[:, b]
        k = _PAIR_INDEX[(a, b)]
        ok_ab = ok_a & ok_b & freq.binary_ok[:, k] & ~freq.binary_ar_implied[:, k]
        no_val = jnp.full(n, NO_VALUE, jnp.int32)
        parts.append((join_val, cc.create(bit_a, secondary_condition=proj_bit),
                      triples[:, a], no_val, ok_a))
        parts.append((join_val, cc.create(bit_b, secondary_condition=proj_bit),
                      triples[:, b], no_val, ok_b))
        parts.append((join_val, cc.create(bit_a, bit_b, proj_bit),
                      triples[:, a], triples[:, b], ok_ab))

    if not parts:
        e = jnp.zeros(0, jnp.int32)
        return JoinCandidates(e, e, e, e, jnp.zeros(0, bool))

    return JoinCandidates(
        join_val=jnp.concatenate([p[0] for p in parts]),
        code=jnp.concatenate([jnp.full(n, p[1], jnp.int32) for p in parts]),
        v1=jnp.concatenate([p[2] for p in parts]),
        v2=jnp.concatenate([p[3] for p in parts]),
        valid=jnp.concatenate([p[4] for p in parts]),
    )
