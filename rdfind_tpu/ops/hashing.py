"""Integer mixing hashes for bucket routing.

Plays the role of the reference's MurmurHash (rdfind-util/.../ie/ucd/murmur/
MurmurHash.java:30-207, used for partitioning and Bloom filters): deterministic,
well-mixed 32-bit hashes computed elementwise on device.  Uses the splitmix32
finalizer (public-domain construction) on uint32 lanes — multiply/xor/shift only,
ideal for TPU vector units.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mix32(x):
    """splitmix32 finalizer over int32/uint32 arrays; returns uint32."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


def hash_cols(cols, seed: int = 0):
    """Combine several int32 columns into one well-mixed uint32 hash."""
    h = jnp.uint32(0x9E3779B9 * (seed + 1) & 0xFFFFFFFF)
    for c in cols:
        h = mix32(c.astype(jnp.uint32) ^ (h + jnp.uint32(0x9E3779B9)))
    return h


def bucket_of(cols, num_buckets, seed: int = 0):
    """Deterministic bucket id in [0, num_buckets) from int32 key columns."""
    return (hash_cols(cols, seed) % jnp.uint32(num_buckets)).astype(jnp.int32)


def host_bucket_of(cols, num_buckets: int, seed: int = 0):
    """Numpy replica of bucket_of — bit-identical to the device law.

    This is the single routing law shared by the sharded exchange planner,
    the elastic-resume re-shard (models/sharded.py delegates its host
    replica here), and the delta engine's bucket ownership map
    (runtime/delta.py): a value hashes to the same bucket on device, on a
    resumed mesh, and in an incremental run, so "which bucket owns this
    join value" has exactly one answer everywhere.
    """
    import numpy as np
    with np.errstate(over="ignore"):
        h = np.uint32(0x9E3779B9 * (seed + 1) & 0xFFFFFFFF)
        for c in cols:
            x = np.asarray(c).astype(np.uint32) ^ (h + np.uint32(0x9E3779B9))
            x = (x ^ (x >> np.uint32(16))) * np.uint32(0x85EBCA6B)
            x = (x ^ (x >> np.uint32(13))) * np.uint32(0xC2B2AE35)
            h = x ^ (x >> np.uint32(16))
        return (np.asarray(h, np.uint32) % np.uint32(num_buckets)).astype(
            np.int32)


def digest_fold(cols, valid, seed: int = 0):
    """One order-invariant content-digest lane over a masked row set: the
    per-row hash_cols mixes, invalid rows zeroed, summed mod 2^32.

    Returned as an int32 scalar (bitcast, not value-convert) so a psum over
    per-device partials — int32 two's-complement wraparound — equals the
    uint32 wraparound sum over ALL rows bit for bit.  The commutative sum
    makes the lane invariant to row order and device partitioning; the host
    replica is obs/integrity._fold.
    """
    h = jnp.where(valid, hash_cols(cols, seed), jnp.uint32(0))
    return jax.lax.bitcast_convert_type(jnp.sum(h, dtype=jnp.uint32),
                                        jnp.int32)
