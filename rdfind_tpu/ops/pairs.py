"""CIND-evidence pair generation — the quadratic hot path, as rotations.

The reference emits, per join line, one evidence per dependent capture carrying the
whole line as referenced set (CreateAllCindCandidates.scala:106-121) and k-way
intersects them (IntersectCindCandidates.scala:14-51).  Equivalent count formulation
used here: for captures d, r

    CIND d ⊆ r  <=>  cooc(d, r) == |lines containing d|  (and support >= min_support)

so evidence extraction becomes emitting all ordered co-occurrence pairs and counting.

Pair enumeration is rotation-based: for a line of length L laid out contiguously,
rotation j (1 <= j < L) pairs each element with the one j slots ahead (mod L).  The
whole enumeration is one flat repeat + gather with a *static* output capacity — a
constant number of XLA ops, fully jittable, however skewed the line-size distribution
is.  Total real work is sum_l L_l (L_l - 1), the evidence count itself.

All functions here are fixed-shape and mask-based (see ops/segments.py conventions):
rows beyond the valid count are garbage and must be masked by callers.
"""

from __future__ import annotations

import jax.lax
import jax.numpy as jnp
import jax.ops
import numpy as np

from . import segments

# Saturation bound for pair-count prefix sums: large enough that any real capacity
# is below it, small enough that a single add can never wrap int32.
# Plain int (not jnp.int32): a module-scope device array would initialize the
# default backend at import time — on this image that is the remote-TPU tunnel,
# which must not be touched by CPU-only runs (round-1 bench/dryrun hangs).
SAT = np.int32(1 << 30)


def saturating_cumsum(x):
    """Inclusive prefix sum of non-negative int32 with saturation at SAT.

    min(a+b, SAT) is associative for non-negative operands, so this lowers to an
    O(log n) associative scan; unlike a plain cumsum it cannot wrap int32, which
    keeps overflow *detection* exact however quadratic the pair counts get.
    """
    x = jnp.minimum(x, SAT)
    return jax.lax.associative_scan(lambda a, b: jnp.minimum(a + b, SAT), x)


def line_layout(line_val, n_valid):
    """Run layout over candidate rows sorted by join value, valid-prefix masked.

    `line_val` must be sorted ascending among its first `n_valid` rows (rows beyond
    are garbage).  Returns (pos, length, start_idx, total_pairs):
      pos       -- position of each row within its line;
      length    -- line length (1 for invalid rows, so they contribute no pairs);
      start_idx -- index of the line's first row;
      total_pairs -- scalar, sum of length*(length-1) over lines.
    """
    n = line_val.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    valid = idx < n_valid
    jv = jnp.where(valid, line_val, segments.SENTINEL)
    starts = segments.run_starts([jv])
    gid = jnp.cumsum(starts).astype(jnp.int32) - 1
    counts = jax.ops.segment_sum(valid.astype(jnp.int32), gid, num_segments=n)
    length = jnp.where(valid, counts[gid], 1)
    run_start = jax.lax.cummax(jnp.where(starts, idx, 0))
    pos = idx - run_start
    # Saturating sum: exact below SAT, pinned at SAT beyond -- callers compare it
    # against capacities far below SAT, so overflow handling stays correct.
    cum = saturating_cumsum(length - 1)
    total_pairs = cum[-1] if n else jnp.int32(0)
    return pos, length, run_start, total_pairs


def emit_pair_indices(pos, length, start_idx, capacity: int,
                      balanced: bool = False, emit=None):
    """Row/partner indices of all ordered co-occurrence pairs, statically padded.

    Returns (row, partner, pair_valid): gather payload columns at `row` (dependent)
    and `partner` (referenced) to materialize pairs.  Rows beyond the true total are
    garbage (masked by pair_valid).  If total pairs exceed `capacity`, the excess is
    truncated — callers must compare line_layout's total against capacity and
    retry/chunk on overflow.

    `emit` (optional bool per row) suppresses emission for rows where it is
    False: those rows take ZERO output slots (they still appear as partners of
    emitting rows).  This is what makes dependent-side restriction reduce the
    required capacity — a masked-after-emission design would still allocate
    the full quadratic — and it is the mechanism behind both the S2L level
    masks and the bounded-memory dep-slice pair passes.

    balanced=True emits each *unordered* pair exactly once — rotations
    j <= (L-1)//2 per row, plus (for even L) the antipodal rotation L/2 for the
    first half of positions.  This is the TPU-rotation form of the reference's
    ring-distance ownership (AbstractExtractBalancedUnaryUnaryOverlapCandidates
    .scala:64-120): per line, every element owns ~half its partners, total
    emission L*(L-1)/2, with even per-element load.  Callers must symmetrize
    the merged counts (ownership is positional, so the same capture pair can
    be owned in either direction in different lines).
    """
    n = pos.shape[0]
    if balanced:
        reps = ((length - 1) // 2) + ((length % 2 == 0) & (pos < length // 2))
        reps = reps.astype(jnp.int32)
    else:
        reps = length - 1
    if emit is not None:
        reps = jnp.where(emit, reps, 0)
    # Saturating prefix sum instead of jnp.repeat's internal cumsum: immune to int32
    # wrap on quadratic totals (see saturating_cumsum).
    cum = saturating_cumsum(reps)
    total = cum[-1]
    out_idx = jnp.arange(capacity, dtype=jnp.int32)
    pair_valid = out_idx < total
    # Row owning output slot k: first row whose inclusive cumsum exceeds k.
    row = jnp.searchsorted(cum, out_idx, side="right").astype(jnp.int32)
    row = jnp.clip(row, 0, n - 1)
    block_start = cum[row] - reps[row]
    j = out_idx - block_start + 1
    partner = start_idx[row] + (pos[row] + j) % length[row]
    partner = jnp.clip(partner, 0, n - 1)  # tail rows repeat the last real row; masked
    return row, partner, pair_valid
