"""CIND-evidence pair generation — the quadratic hot path, as rotations.

The reference emits, per join line, one evidence per dependent capture carrying the
whole line as referenced set (CreateAllCindCandidates.scala:106-121) and k-way
intersects them (IntersectCindCandidates.scala:14-51).  Equivalent count formulation
used here: for captures d, r

    CIND d ⊆ r  <=>  cooc(d, r) == |lines containing d|  (and support >= min_support)

so evidence extraction becomes emitting all ordered co-occurrence pairs and counting.

Pair enumeration is rotation-based: for a line of length L laid out contiguously,
rotation j (1 <= j < L) pairs each element with the one j slots ahead (mod L).  The
whole enumeration is one flat repeat + gather with a *static* output capacity — a
constant number of XLA ops, fully jittable, however skewed the line-size distribution
is.  Total real work is sum_l L_l (L_l - 1), the evidence count itself.

All functions here are fixed-shape and mask-based (see ops/segments.py conventions):
rows beyond the valid count are garbage and must be masked by callers.
"""

from __future__ import annotations

import jax.lax
import jax.numpy as jnp
import jax.ops

from . import segments


def line_layout(line_val, n_valid):
    """Run layout over candidate rows sorted by join value, valid-prefix masked.

    `line_val` must be sorted ascending among its first `n_valid` rows (rows beyond
    are garbage).  Returns (pos, length, start_idx, total_pairs):
      pos       -- position of each row within its line;
      length    -- line length (1 for invalid rows, so they contribute no pairs);
      start_idx -- index of the line's first row;
      total_pairs -- scalar, sum of length*(length-1) over lines.
    """
    n = line_val.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    valid = idx < n_valid
    jv = jnp.where(valid, line_val, segments.SENTINEL)
    starts = segments.run_starts([jv])
    gid = jnp.cumsum(starts).astype(jnp.int32) - 1
    counts = jax.ops.segment_sum(valid.astype(jnp.int32), gid, num_segments=n)
    length = jnp.where(valid, counts[gid], 1)
    run_start = jax.lax.cummax(jnp.where(starts, idx, 0))
    pos = idx - run_start
    total_pairs = (length - 1).sum()
    return pos, length, run_start, total_pairs


def emit_pairs(line_cap, pos, length, start_idx, capacity: int):
    """All ordered (dep, ref) co-occurrence pairs, padded to a static capacity.

    Returns (dep, ref, pair_valid).  Rows beyond the true total carry SENTINEL keys.
    `capacity` must be >= total_pairs (callers size it from line_layout's total).
    """
    n = line_cap.shape[0]
    reps = length - 1
    total = reps.sum()
    row = jnp.repeat(jnp.arange(n, dtype=jnp.int32), reps, total_repeat_length=capacity)
    block_start = jnp.repeat(jnp.cumsum(reps).astype(jnp.int32) - reps, reps,
                             total_repeat_length=capacity)
    out_idx = jnp.arange(capacity, dtype=jnp.int32)
    pair_valid = out_idx < total
    j = out_idx - block_start + 1
    partner = start_idx[row] + (pos[row] + j) % length[row]
    partner = jnp.clip(partner, 0, n - 1)  # tail rows repeat the last real row; masked
    dep = jnp.where(pair_valid, line_cap[row], segments.SENTINEL)
    ref = jnp.where(pair_valid, line_cap[partner], segments.SENTINEL)
    return dep, ref, pair_valid
