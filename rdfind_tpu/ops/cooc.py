"""Dense co-occurrence matmul: the MXU formulation of the quadratic pair phase.

The reference's hot path emits, per join line, every ordered pair of captures
as a CIND evidence and intersects evidence sets per dependent
(CreateAllCindCandidates.scala:106-121, IntersectCindCandidates.scala:14-51).
The count reformulation used across this repo tests `cooc(d, r) == support(d)`
instead.  This module computes the *entire* cooc matrix as one blocked matmul:

    M    : (lines x captures) 0/1 membership in HBM — int8 by default on
           int8-MXU backends (one-time runtime probe), bf16 elsewhere or
           via RDFIND_COOC_DTYPE=bf16
    cooc : M^T M on the MXU — int32 accumulation for int8 (exact to int32
           counts), f32 for bf16 (exact while lines < 2^24)

which replaces the sort-dominated chunked pair pipeline (r2 bench: lexsort over
every 4M-pair chunk + a host sync per chunk left the MXU idle and lost 13x to
one Python core).  Skew vanishes by construction — a giant join line is just a
dense row of M, no splitting or rebalancing required on one chip.

The CIND test, support filter, diagonal and trivially-implied-pair masks all
run elementwise on (tile x captures) blocks of cooc, and the surviving boolean
matrix is bit-packed on device so the host pulls C^2/32 bytes, not C^2 ints
(the axon tunnel makes transfer volume expensive).  The host then just
np.unpackbits + nonzero to read off CIND pairs.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from .. import conditions as cc
from ..runtime import dispatch
from . import segments

# Dep-tile rows per cooc block: (DT x C_pad) f32 tile = 16 MB per 1k captures.
DEFAULT_TILE = 4096
# Dense membership budget: M is (L_pad x C_pad) x elem_bytes in HBM (2 for
# bf16, 1 for int8).  v5e has 16 GB; leave room for the cooc tile, capture
# tables, and XLA scratch.
DENSE_M_BUDGET_BYTES = int(os.environ.get("RDFIND_DENSE_M_BUDGET", 6 << 30))
# bf16 mode's f32 accumulation is exact up to 2^24 lines; past that the bf16
# dense plan must fall back (int8 mode accumulates in int32 — no such cap).
MAX_LINES_EXACT_F32 = 1 << 24

# Membership element type for the cooc matmuls.  "auto" (the default) probes
# the backend once and picks int8 wherever the hardware int8 matmul path
# pays off (the TPU MXU: int8 halves membership HBM, doubles the v5e peak —
# 394 int8 TOPS vs 197 bf16 TFLOPS — and its int32 accumulation is exact far
# past f32's 2^24-line cap), falling back to bf16 elsewhere (XLA CPU's
# generic int8 loops are slower than bf16).  RDFIND_COOC_DTYPE pins either
# mode explicitly; outputs are bit-identical.
COOC_DTYPE = os.environ.get("RDFIND_COOC_DTYPE", "auto")
if COOC_DTYPE not in ("auto", "bf16", "int8"):
    raise ValueError(f"RDFIND_COOC_DTYPE must be auto, bf16 or int8, "
                     f"got {COOC_DTYPE!r}")

# Tile-schedule padding policy: on (default), dense plans pad to tile
# multiples (occupancy > 0.9 on real workloads) and skip all-padding dep
# tiles; RDFIND_TILE_SCHEDULE=0 restores the legacy pow2-bucketed plan
# (roughly 2x issued FLOPs in the worst case, but maximal compiled-program
# reuse across datasets).  Both policies are bit-identical in output —
# differential-tested across all four traversal strategies.
TILE_SCHEDULE = os.environ.get("RDFIND_TILE_SCHEDULE", "1").lower() \
    not in ("0", "false", "no")

# Row padding granule of the membership matrix under the tile schedule: a
# multiple of every dtype's sublane tile (f32 8, bf16 16, int8 32) with
# enough slack that distinct tiny test datasets still bucket together.
LINE_MULT = 256
# Column granule: the MXU lane width and the 32-bit packing word both divide
# 128, and every dep-tile width is a multiple of it.
CAP_MULT = 128


@functools.lru_cache(maxsize=1)
def int8_matmul_supported() -> bool:
    """One-time runtime probe: does this backend lower an int8 x int8 matmul
    with int32 accumulation?  Checked eagerly on a tiny product so the auto
    dtype can fall back to bf16 before any hot-path program is traced."""
    try:
        a = jnp.ones((8, 8), jnp.int8)
        out = jax.lax.dot_general(a, a, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return bool(jax.device_get(out)[0, 0] == 8)
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def _int8_pays_off() -> bool:
    """Whether "auto" resolves to int8: the matmul must lower AND the backend
    must have a hardware int8 path worth taking.  The TPU MXU runs int8 at
    2x its bf16 rate (v5e: 394 TOPS vs 197 TFLOPS); XLA *CPU* lowers int8
    GEMM to generic loops measured ~4x SLOWER than bf16, so the CPU proxy
    keeps bf16 and the wall clock does not regress."""
    return jax.default_backend() == "tpu" and int8_matmul_supported()


def resolved_cooc_dtype() -> str:
    """The membership dtype actually in effect ("bf16" or "int8").

    Reads COOC_DTYPE at call time (tests monkeypatch the module attribute);
    only the backend probes behind "auto" are cached."""
    if COOC_DTYPE != "auto":
        return COOC_DTYPE
    return "int8" if _int8_pays_off() else "bf16"


def round_up(n: int, mult: int) -> int:
    """Smallest multiple of `mult` >= max(n, 1)."""
    return -(-max(int(n), 1) // mult) * mult


def tile_for(c_pad: int, tile_max: int = DEFAULT_TILE) -> int:
    """Largest dep-tile width that divides `c_pad`, is a power-of-two
    multiple of CAP_MULT, and stays <= tile_max.

    Divisibility keeps every host-loop tile start exact under dynamic_slice's
    edge clamping (a clamped start would silently recompute earlier rows and
    emit duplicate pairs); the pow2 structure keeps tile widths MXU-friendly.
    """
    assert c_pad % CAP_MULT == 0, c_pad
    m = c_pad // CAP_MULT
    t = CAP_MULT * (m & -m)  # largest pow2 divisor of m, in columns
    return max(CAP_MULT, min(t, tile_max, c_pad))


def cap_pad(num_caps: int, mult: int = CAP_MULT) -> int:
    """Capture-axis padding under the active policy: tile-multiple (tight)
    when TILE_SCHEDULE is on, pow2-bucketed otherwise.  `mult` raises the
    granule (the sharded sketch path needs device-count divisibility)."""
    if TILE_SCHEDULE:
        return round_up(num_caps, mult)
    return round_up(max(CAP_MULT, segments.pow2_capacity(num_caps)), mult)


@dataclasses.dataclass(frozen=True)
class DensePlan:
    """Shape plan + tile schedule for the dense cooc path.

    The schedule (dep_tile_starts) enumerates the dep tiles that contain at
    least one real capture; all-padding tiles are never dispatched, and the
    occupancy accounting (real_flops / issued_flops) is what benches report
    as occupancy-corrected MFU instead of padded-FLOP MFU.
    """

    l_pad: int
    c_pad: int
    tile: int
    n_lines: int
    num_caps: int
    dtype: str

    def __iter__(self):  # legacy (l_pad, c_pad, tile) unpacking
        return iter((self.l_pad, self.c_pad, self.tile))

    @property
    def dep_tile_starts(self) -> tuple:
        """Dep-tile starts whose tile intersects [0, num_caps)."""
        return tuple(lo for lo in range(0, self.c_pad, self.tile)
                     if lo < self.num_caps)

    @property
    def n_tiles(self) -> int:
        return self.c_pad // self.tile

    @property
    def n_tiles_skipped(self) -> int:
        return self.n_tiles - len(self.dep_tile_starts)

    @property
    def issued_flops(self) -> int:
        """MACs*2 actually dispatched by the scheduled tile sweep."""
        return 2 * self.l_pad * self.c_pad * self.tile \
            * len(self.dep_tile_starts)

    @property
    def real_flops(self) -> int:
        """MACs*2 the unpadded workload needs."""
        return 2 * self.n_lines * self.num_caps * self.num_caps

    @property
    def occupancy(self) -> float:
        return self.real_flops / max(self.issued_flops, 1)

    def describe(self) -> dict:
        """Occupancy record for run stats / --debug / bench JSON."""
        return {
            "policy": "tile" if TILE_SCHEDULE else "pow2",
            "dtype": self.dtype,
            "l_real": self.n_lines, "l_pad": self.l_pad,
            "c_real": self.num_caps, "c_pad": self.c_pad,
            "tile": self.tile,
            "n_tiles": self.n_tiles,
            "n_tiles_skipped": self.n_tiles_skipped,
            "issued_flops": self.issued_flops,
            "real_flops": self.real_flops,
            "occupancy": round(self.occupancy, 4),
        }


def cooc_dot(a, b, dims=((0,), (0,))):
    """Exact integer counts from a 0/1-matrix product: accumulate in the
    dtype-matched exact accumulator (f32 for bf16, int32 for int8)."""
    acc = jnp.int32 if a.dtype == jnp.int8 else jnp.float32
    return jax.lax.dot_general(
        a, b, (dims, ((), ())), preferred_element_type=acc).astype(jnp.int32)


def pack_bool(x):
    """(R, C) bool/0-1 -> (R, ceil(C/32)) uint32, little bit order per word.

    The single packing implementation shared by every device stage; the host
    inverse is unpack_cind_bits (np.unpackbits bitorder="little").
    """
    r, c = x.shape
    if c % 32:
        x = jnp.pad(x, ((0, 0), (0, 32 - c % 32)))
        c = x.shape[1]
    lanes = x.astype(jnp.uint32).reshape(r, c // 32, 32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return (lanes * weights[None, None, :]).sum(axis=2, dtype=jnp.uint32)


def dense_plan(n_lines: int, num_caps: int, tile: int = DEFAULT_TILE):
    """DensePlan for the dense path, or None when it does not fit.

    c_pad is always a multiple of CAP_MULT=128 (MXU lanes and 32-bit packing)
    and of the tile (exact dep-tile starts under dynamic_slice clamping).
    Under the default tile-multiple policy l_pad/c_pad hug the real shape
    (occupancy > 0.9 on non-degenerate workloads); RDFIND_TILE_SCHEDULE=0
    restores the legacy pow2 buckets, whose worst case issues ~2x the rows
    and ~2x the columns (the headline workload measured ~56% row occupancy).
    """
    if n_lines == 0 or num_caps == 0:
        return None
    dtype = resolved_cooc_dtype()
    if dtype != "int8" and n_lines >= MAX_LINES_EXACT_F32:
        return None  # int8 accumulates in int32: exact to 2^31 counts
    if TILE_SCHEDULE:
        l_pad = round_up(n_lines, LINE_MULT)
        c_pad = cap_pad(num_caps)
        tile = tile_for(c_pad, tile)
    else:
        # Legacy pow2 buckets: maximal compiled-program reuse across datasets
        # (segments.pow2_capacity); c_pad a pow2 >= 128 is automatically a
        # multiple of the (pow2) tile.
        l_pad = max(8, segments.pow2_capacity(n_lines))
        c_pad = cap_pad(num_caps)
        tile = min(tile, c_pad)
    elem_bytes = 1 if dtype == "int8" else 2
    if l_pad * c_pad * elem_bytes > DENSE_M_BUDGET_BYTES:
        return None
    return DensePlan(l_pad=l_pad, c_pad=c_pad, tile=tile, n_lines=n_lines,
                     num_caps=num_caps, dtype=dtype)


@functools.partial(jax.jit, static_argnames=("l_pad", "c_pad", "dtype"))
def _build_membership(line_gid, line_cap, valid, *, l_pad: int, c_pad: int,
                      dtype: str):
    dt = jnp.int8 if dtype == "int8" else jnp.bfloat16
    li = jnp.where(valid, line_gid, l_pad)
    ci = jnp.where(valid, line_cap, c_pad)
    m = jnp.zeros((l_pad, c_pad), dt)
    return m.at[li, ci].set(jnp.asarray(1, dt), mode="drop")


def build_membership(line_gid, line_cap, valid, *, l_pad: int, c_pad: int,
                     dtype: str | None = None):
    """Scatter (line, capture) rows into the (l_pad, c_pad) 0/1 matrix.

    The element type (resolved_cooc_dtype() by default; `dtype` overrides)
    is a STATIC jit key: the inputs' avals don't carry it, so it must key the
    cache explicitly or a dtype flip would silently reuse the other mode's
    compiled program.  Downstream consumers take `m` itself, whose aval
    re-keys them."""
    return _build_membership(line_gid, line_cap, valid, l_pad=l_pad,
                             c_pad=c_pad,
                             dtype=resolved_cooc_dtype() if dtype is None
                             else dtype)


@functools.partial(jax.jit, static_argnames=("tile",))
def cooc_cind_tile(m, dep_lo, dep_count, cap_code, cap_v1, cap_v2,
                   min_support, *, tile: int):
    """One (tile x C_pad) block of the CIND matrix, bit-packed along refs.

    m: (l_pad, c_pad) membership; dep_lo: first dep capture id of this tile;
    dep_count/cap_*: (c_pad,) per-capture support and identity columns.
    Returns (tile, c_pad // 32) uint32 where bit r of word w in row d means
    "capture dep_lo+d is CIND-included in capture 32w+r".

    The elementwise masks mirror _stage_merge (models/allatonce.py): support
    test, min_support, no self-pairs, and the trivially-implied-pair rule of
    data/Condition.scala:35-43.
    """
    c_pad = m.shape[1]
    m_tile = jax.lax.dynamic_slice(m, (0, dep_lo), (m.shape[0], tile))
    cooc = cooc_dot(m_tile, m)

    d_idx = dep_lo + jnp.arange(tile, dtype=jnp.int32)
    d_safe = jnp.clip(d_idx, 0, c_pad - 1)
    support = dep_count[d_safe][:, None]
    is_cind = (cooc == support) & (support >= min_support)
    is_cind &= d_idx[:, None] != jnp.arange(c_pad, dtype=jnp.int32)[None, :]

    d_code = cap_code[d_safe][:, None]
    d_v1 = cap_v1[d_safe][:, None]
    d_v2 = cap_v2[d_safe][:, None]
    r_code = cap_code[None, :]
    implied = cc.is_subcode(r_code, d_code) & jnp.where(
        cc.first_subcapture(d_code) == r_code,
        cap_v1[None, :] == d_v1, cap_v1[None, :] == d_v2)
    return pack_bool(is_cind & ~implied)


def _inbounds(packed, rows, cols):
    """Zero out words outside the [0, rows) x [0, cols) bit region.

    rows/cols are TRACED operands (not static jit keys): the compiled
    programs key only on the pow2-bucketed packed shape, preserving the
    repo's program-reuse policy across lattice levels and datasets."""
    word_idx = jnp.arange(packed.shape[1], dtype=jnp.int32)
    partial = jnp.clip(cols - word_idx * 32, 0, 32)
    # Shift stays in [0, 31]: uint32 << 32 is implementation-defined in XLA,
    # so the partial == 32 case selects the full mask without ever evaluating
    # an out-of-range shift (even in an unselected where branch).
    low = (jnp.uint32(1) << jnp.minimum(partial, 31).astype(jnp.uint32)) \
        - jnp.uint32(1)
    col_mask = jnp.where(partial >= 32, jnp.uint32(0xFFFFFFFF), low)
    row_ok = jnp.arange(packed.shape[0], dtype=jnp.int32) < rows
    return jnp.where(row_ok[:, None], packed & col_mask[None, :], 0)


@jax.jit
def packed_count(packed, rows, cols):
    """Set bits in the in-bounds region; int32 is exact under the
    EXTRACT_DEVICE_ELEMS <= 2^28-bit gate callers apply."""
    return jax.lax.population_count(_inbounds(packed, rows, cols)).sum(
        dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("cap",))
def packed_nonzero(packed, rows, cols, *, cap: int):
    """(row, col) indices of the first `cap` in-bounds set bits (row-major)."""
    from . import sketch

    d, r = jnp.nonzero(sketch.unpack_planes(_inbounds(packed, rows, cols)),
                       size=cap, fill_value=0)
    return d.astype(jnp.int32), r.astype(jnp.int32)


# Device extraction materializes the unpacked relation plus nonzero's scan
# intermediates; past this element count a relation decodes in row strips so
# each strip's intermediates stay under the bound.  2^28 bits also keeps
# packed_count's int32 sum exact.
EXTRACT_DEVICE_ELEMS = 1 << 28

# Device bytes pinned by pending sized-nonzero outputs before a batched pull
# flush.  Without it, near-dense relations could pin index pairs proportional
# to the total set-bit count (32 GB for a saturated 4096 x 2^20-bit sweep)
# while waiting for one giant device_get.
PULL_BYTES_BUDGET = 1 << 28


def _flush_pulls(pend):
    """One batched device_get of pending sized-nonzero outputs.

    pend: list of (key, count, (d_dev, r_dev)).  Returns [(key, d, r)] with
    host int64 arrays truncated to their exact counts."""
    flat = iter(jax.device_get([x for _, _, dr in pend for x in dr]))
    out = []
    for key, c, _ in pend:
        d, r = next(flat), next(flat)
        out.append((key, d[:c].astype(np.int64), r[:c].astype(np.int64)))
    return out


def extract_packed(packed, rows: int, cols: int):
    """Decode a packed bool relation -> host (row, col) int64 index arrays.

    Small enough relations decode on device — an exact popcount dispatch,
    then a sized nonzero — so the host pulls one scalar plus exactly the
    set-bit index pairs, never the bit matrix itself (the multi-MB pull +
    host unpackbits scan dominated the lattice's non-matmul wall clock over
    the tunnel).  Oversized relations decode in row strips: each strip's
    unpacked planes + nonzero intermediates stay <= EXTRACT_DEVICE_ELEMS
    bits on device, counts and index pulls are batched so the host syncs
    twice total, and the bit matrix still never crosses the tunnel (the r4
    host fallback pulled C^2/32 bytes per oversized tile — strategy 2's
    second measured bottleneck)."""
    words = packed.shape[1]
    total_bits = packed.shape[0] * words * 32
    if total_bits <= EXTRACT_DEVICE_ELEMS:
        return extract_packed_iter([lambda: (packed, rows, cols)],
                                   total_bits)[0]
    # Strips are just same-shaped small tiles decoded through the shared
    # batched iterator; a partial final strip compiles its own (smaller)
    # program, which the iterator's per-shape thunks already allow (the
    # tile-multiple c_pad policy means words need not be pow2).  tile_bits
    # is clamped for the pathological one-row-over-budget shape
    # (words*32 > EXTRACT_DEVICE_ELEMS), where a single row must decode in
    # one shot anyway and clamping avoids bouncing back into this strip path.
    h = max(1, EXTRACT_DEVICE_ELEMS // (words * 32))
    los = list(range(0, min(rows, packed.shape[0]), h))

    def make(lo):
        return lambda: (packed[lo:lo + h], min(rows - lo, h), cols)

    pairs = extract_packed_iter([make(lo) for lo in los],
                                min(h * words * 32, EXTRACT_DEVICE_ELEMS))
    out_d = [d + lo for lo, (d, _) in zip(los, pairs) if d.size]
    out_r = [r for _, (d, r) in zip(los, pairs) if d.size]
    if not out_d:
        z = np.zeros(0, np.int64)
        return z, z
    return np.concatenate(out_d), np.concatenate(out_r)


def extract_packed_iter(thunks, tile_bits: int):
    """Decode a stream of packed tiles with batched, pipelined host syncs.

    thunks: callables dispatching one tile each, returning (packed, rows,
    cols).  Tiles MAY differ in shape (small_to_large batches its mixed
    lattice relations through one call); `tile_bits` must be an UPPER BOUND
    on any tile's packed bits — it is what bounds how many tiles sit on
    device awaiting decode (EXTRACT_DEVICE_ELEMS per batch), so an
    underestimate breaks the residency math (ADVICE r5).  Each batch costs
    one counts sync; index pulls flush under PULL_BYTES_BUDGET.

    Pipelined schedule (unless RDFIND_SYNC_PASSES forces the serial one):
    batch i+1's tiles are dispatched BEFORE batch i's counts are pulled, so
    tile compute overlaps the count readback and index pulls; the batch size
    is halved to keep the two-batches-in-flight residency inside the same
    EXTRACT_DEVICE_ELEMS budget.  Oversized tiles fall through to
    extract_packed's strip decode.  Returns [(d, r)] host int64 arrays in
    thunk order — the shared decode behind the dense strategy-0 sweep and
    strategy 2's candidate generation.
    """
    if tile_bits > EXTRACT_DEVICE_ELEMS:
        return [extract_packed(*t()) for t in thunks]
    out = [None] * len(thunks)
    pipelined = not dispatch.sync_passes_forced() and len(thunks) > 1
    batch = max(1, EXTRACT_DEVICE_ELEMS // tile_bits // (2 if pipelined
                                                         else 1))
    empty = (np.zeros(0, np.int64), np.zeros(0, np.int64))

    def launch(lo):
        group = [(lo + j, *t()) for j, t in enumerate(thunks[lo:lo + batch])]
        counts = [packed_count(p, jnp.int32(r), jnp.int32(c))
                  for _, p, r, c in group]
        dispatch.stage_to_host(counts)
        return group, counts

    def drain_batch(group, counts):
        counts = jax.device_get(counts)
        pend, pend_bytes = [], 0

        def drain():
            nonlocal pend, pend_bytes
            for k, d, r in _flush_pulls(pend):
                out[k] = (d, r)
            pend, pend_bytes = [], 0

        for n, (k, p, rows, cols) in zip(counts, group):
            n = int(n)
            if not n:
                out[k] = empty
                continue
            cap = segments.pow2_capacity(n)
            pend.append((k, n, packed_nonzero(p, jnp.int32(rows),
                                              jnp.int32(cols), cap=cap)))
            dispatch.stage_to_host(pend[-1][2])
            pend_bytes += 8 * cap
            if pend_bytes >= PULL_BYTES_BUDGET:
                drain()
        drain()

    prev = None
    for lo in range(0, len(thunks), batch):
        cur = launch(lo)
        if not pipelined:
            drain_batch(*cur)
            continue
        if prev is not None:
            drain_batch(*prev)
        prev = cur
    if prev is not None:
        drain_batch(*prev)
    return out


def unpack_cind_bits(packed: np.ndarray, c_pad: int) -> np.ndarray:
    """(tile, c_pad//32) uint32 -> (tile, c_pad) 0/1 uint8 on host."""
    return np.unpackbits(
        np.ascontiguousarray(packed).view(np.uint8),
        axis=1, bitorder="little")[:, :c_pad]


def discover_pairs_dense(m, dep_count, cap_code, cap_v1, cap_v2, min_support,
                         num_caps: int, tile: int, starts=None):
    """Run the tiled cooc pass; return (dep_id, ref_id, support) numpy arrays.

    m: (l_pad, c_pad) device membership matrix.  The host loops over the
    scheduled dep tiles (`starts`, default: every tile intersecting
    [0, num_caps) — all-padding tiles are never dispatched) sending the
    packed CIND blocks, then decodes them on device: one batched pull of all
    tile popcounts, one batched pull of the sized nonzeros — only the
    set-bit index pairs ever reach the host (same two-phase decode as
    extract_packed, batched across tiles).
    """
    c_pad = m.shape[1]
    dep_count_d = jnp.asarray(dep_count, jnp.int32)
    code_d = jnp.asarray(cap_code, jnp.int32)
    v1_d = jnp.asarray(cap_v1, jnp.int32)
    v2_d = jnp.asarray(cap_v2, jnp.int32)
    ms = jnp.int32(min_support)

    los = list(starts) if starts is not None else list(range(0, num_caps,
                                                             tile))

    def make(lo):
        return lambda: (cooc_cind_tile(m, jnp.int32(lo), dep_count_d, code_d,
                                       v1_d, v2_d, ms, tile=tile),
                        min(num_caps - lo, tile), num_caps)

    pairs = extract_packed_iter([make(lo) for lo in los], tile * c_pad)
    deps = [d + lo for lo, (d, _) in zip(los, pairs) if d.size]
    refs = [r for _, (d, r) in zip(los, pairs) if d.size]
    dep_id = np.concatenate(deps) if deps else np.zeros(0, np.int64)
    ref_id = np.concatenate(refs) if refs else np.zeros(0, np.int64)
    support = np.asarray(dep_count)[dep_id] if dep_id.size else np.zeros(0, np.int64)
    return dep_id, ref_id, support
