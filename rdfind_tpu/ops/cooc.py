"""Dense co-occurrence matmul: the MXU formulation of the quadratic pair phase.

The reference's hot path emits, per join line, every ordered pair of captures
as a CIND evidence and intersects evidence sets per dependent
(CreateAllCindCandidates.scala:106-121, IntersectCindCandidates.scala:14-51).
The count reformulation used across this repo tests `cooc(d, r) == support(d)`
instead.  This module computes the *entire* cooc matrix as one blocked matmul:

    M    : (lines x captures) 0/1 membership in HBM — int8 by default on
           int8-MXU backends (one-time runtime probe), bf16 elsewhere or
           via RDFIND_COOC_DTYPE=bf16
    cooc : M^T M on the MXU — int32 accumulation for int8 (exact to int32
           counts), f32 for bf16 (exact while lines < 2^24)

which replaces the sort-dominated chunked pair pipeline (r2 bench: lexsort over
every 4M-pair chunk + a host sync per chunk left the MXU idle and lost 13x to
one Python core).  Skew vanishes by construction — a giant join line is just a
dense row of M, no splitting or rebalancing required on one chip.

The CIND test, support filter, diagonal and trivially-implied-pair masks all
run elementwise on (tile x captures) blocks of cooc, and the surviving boolean
matrix is bit-packed on device so the host pulls C^2/32 bytes, not C^2 ints
(the axon tunnel makes transfer volume expensive).  The host then just
np.unpackbits + nonzero to read off CIND pairs.
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from .. import conditions as cc
from ..runtime import dispatch
from . import segments

# Dep-tile rows per cooc block: (DT x C_pad) f32 tile = 16 MB per 1k captures.
DEFAULT_TILE = 4096
# Dense membership budget: M is (L_pad x C_pad) x elem_bytes in HBM (2 for
# bf16, 1 for int8).  v5e has 16 GB; leave room for the cooc tile, capture
# tables, and XLA scratch.
DENSE_M_BUDGET_BYTES = int(os.environ.get("RDFIND_DENSE_M_BUDGET", 6 << 30))
# bf16 mode's f32 accumulation is exact up to 2^24 lines; past that the bf16
# dense plan must fall back (int8 mode accumulates in int32 — no such cap).
MAX_LINES_EXACT_F32 = 1 << 24

# Membership element type for the cooc matmuls.  "auto" (the default) probes
# the backend once and picks int8 wherever the hardware int8 matmul path
# pays off (the TPU MXU: int8 halves membership HBM, doubles the v5e peak —
# 394 int8 TOPS vs 197 bf16 TFLOPS — and its int32 accumulation is exact far
# past f32's 2^24-line cap), falling back to bf16 elsewhere (XLA CPU's
# generic int8 loops are slower than bf16).  RDFIND_COOC_DTYPE pins either
# mode explicitly; outputs are bit-identical.
COOC_DTYPE = os.environ.get("RDFIND_COOC_DTYPE", "auto")
if COOC_DTYPE not in ("auto", "bf16", "int8"):
    raise ValueError(f"RDFIND_COOC_DTYPE must be auto, bf16 or int8, "
                     f"got {COOC_DTYPE!r}")

# Tile-schedule padding policy: on (default), dense plans pad to tile
# multiples (occupancy > 0.9 on real workloads) and skip all-padding dep
# tiles; RDFIND_TILE_SCHEDULE=0 restores the legacy pow2-bucketed plan
# (roughly 2x issued FLOPs in the worst case, but maximal compiled-program
# reuse across datasets).  Both policies are bit-identical in output —
# differential-tested across all four traversal strategies.
TILE_SCHEDULE = os.environ.get("RDFIND_TILE_SCHEDULE", "1").lower() \
    not in ("0", "false", "no")

# Membership-plane width of the packed containment kernel
# (ops/pallas_kernels.py).  "auto" (default) resolves to the narrowest
# sub-byte mode whose matmul path both lowers and pays off on this backend
# (the probes mirror _int8_pays_off): 2 — crumb-packed int2 planes, WK 1024,
# quadrupling int8's contraction lanes per MXU pass at the same VMEM
# budget — where the int2 path engages, else 4 (nibble int4 planes, WK 512),
# else 8 everywhere else, so non-TPU backends keep today's behavior
# untouched.  "8" pins the PR-2 int8 planes unconditionally; "4"/"2" force
# the widened-WK modes (on backends without native sub-byte elements they
# run with int8 elements — the same widened-WK grid, bit-identical, for
# differential testing).  Exactness is unchanged in every mode: planes are
# 0/1, accumulation stays int32.
PLANE_BITS = os.environ.get("RDFIND_PLANE_BITS", "auto")
if PLANE_BITS not in ("auto", "2", "4", "8"):
    raise ValueError(f"RDFIND_PLANE_BITS must be auto, 2, 4 or 8, "
                     f"got {PLANE_BITS!r}")

# Fused verdict + minimality pre-filter on the dense CIND sweep: compute
# `cooc == support`, the support/diagonal masks, and the trivially-implied
# pair rule inside the Pallas kernel epilogue, so the int32 cooc count
# matrix lives only in VMEM scratch and never lands in HBM.  "auto"
# (default) engages on the TPU backend only (the kernel would run in the
# slow interpreter elsewhere, so the CPU proxy keeps the XLA path and its
# wall clock cannot regress); RDFIND_FUSE_VERDICT=0 restores the
# materialized cooc_cind_tile path, =1 forces the fused kernel (interpreted
# off-TPU — the differential-test configuration).
FUSE_VERDICT = os.environ.get("RDFIND_FUSE_VERDICT", "auto")
if FUSE_VERDICT not in ("auto", "0", "1"):
    raise ValueError(f"RDFIND_FUSE_VERDICT must be auto, 0 or 1, "
                     f"got {FUSE_VERDICT!r}")

# K-step DMA latency hiding in the packed containment kernel: "auto"
# (default) replaces the "arbitrary"-dimension double buffering of the K
# grid with an explicit pltpu.emit_pipeline inner loop — operand DMAs are
# issued by a manual pipeline that overlaps the previous chunk's MXU pass —
# wherever the probe (ops/pallas_kernels.emit_pipeline_supported) shows the
# API actually traces and runs on this backend.  The probe fails closed off
# TPU (emit_pipeline asserts the TPU backend even under interpret=True), so
# the CPU proxy keeps the PR-6 grid and its wall clock cannot regress.
# RDFIND_EMIT_PIPELINE=0 pins the PR-6 K-grid double buffering; =1 requests
# the pipelined kernel but still falls back (byte-identical) where the
# probe fails — force can select only paths that exist.
EMIT_PIPELINE = os.environ.get("RDFIND_EMIT_PIPELINE", "auto")
if EMIT_PIPELINE not in ("auto", "0", "1"):
    raise ValueError(f"RDFIND_EMIT_PIPELINE must be auto, 0 or 1, "
                     f"got {EMIT_PIPELINE!r}")

# Sub-tile sparsity skipping: per-(dep-tile x line-block) membership
# popcounts drive the dense sweep schedule — dep tiles whose captures occur
# in no line are dropped outright (both backends), and the fused kernel's
# K-step schedule visits only the nonzero line blocks of each dep tile
# (scalar-prefetched block ids).  Costs one small block-count reduction +
# host pull per sweep; RDFIND_BLOCK_SKIP=0 restores the dense full-range
# schedule, =1 forces it (default "auto" = on whenever the plan has more
# than one block or tile to skip).
BLOCK_SKIP = os.environ.get("RDFIND_BLOCK_SKIP", "auto")
if BLOCK_SKIP not in ("auto", "0", "1"):
    raise ValueError(f"RDFIND_BLOCK_SKIP must be auto, 0 or 1, "
                     f"got {BLOCK_SKIP!r}")

# Row padding granule of the membership matrix under the tile schedule: a
# multiple of every dtype's sublane tile (f32 8, bf16 16, int8 32) with
# enough slack that distinct tiny test datasets still bucket together.
LINE_MULT = 256
# Column granule: the MXU lane width and the 32-bit packing word both divide
# 128, and every dep-tile width is a multiple of it.
CAP_MULT = 128


@functools.lru_cache(maxsize=1)
def int8_matmul_supported() -> bool:
    """One-time runtime probe: does this backend lower an int8 x int8 matmul
    with int32 accumulation?  Checked eagerly on a tiny product so the auto
    dtype can fall back to bf16 before any hot-path program is traced."""
    try:
        a = jnp.ones((8, 8), jnp.int8)
        out = jax.lax.dot_general(a, a, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return bool(jax.device_get(out)[0, 0] == 8)
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def _int8_pays_off() -> bool:
    """Whether "auto" resolves to int8: the matmul must lower AND the backend
    must have a hardware int8 path worth taking.  The TPU MXU runs int8 at
    2x its bf16 rate (v5e: 394 TOPS vs 197 TFLOPS); XLA *CPU* lowers int8
    GEMM to generic loops measured ~4x SLOWER than bf16, so the CPU proxy
    keeps bf16 and the wall clock does not regress."""
    return jax.default_backend() == "tpu" and int8_matmul_supported()


def resolved_cooc_dtype() -> str:
    """The membership dtype actually in effect ("bf16" or "int8").

    Reads COOC_DTYPE at call time (tests monkeypatch the module attribute);
    only the backend probes behind "auto" are cached."""
    if COOC_DTYPE != "auto":
        return COOC_DTYPE
    return "int8" if _int8_pays_off() else "bf16"


@functools.lru_cache(maxsize=1)
def int4_matmul_supported() -> bool:
    """One-time runtime probe: does this backend lower an int4 x int4 matmul
    with int32 accumulation?  XLA CPU rejects sub-byte element conversions
    outright (probed, not assumed — the _repeat_is_tile discipline), so the
    nibble-plane mode emulates with int8 elements there."""
    try:
        a = jnp.ones((8, 8), jnp.int4)
        out = jax.lax.dot_general(a, a, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return bool(jax.device_get(out)[0, 0] == 8)
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def _int4_pays_off() -> bool:
    """Whether "auto" plane bits resolve to 4: the int4 matmul must lower
    AND the backend must have a hardware sub-byte MXU path worth taking —
    the same backend gate as _int8_pays_off (XLA CPU emulates sub-byte
    types poorly where it supports them at all)."""
    return jax.default_backend() == "tpu" and int4_matmul_supported()


def int4_elements_native() -> bool:
    """Whether jnp.int4 planes can actually live in VMEM on this backend.
    Where they cannot, the nibble-WK mode keeps its doubled K-step grid but
    unpacks to int8 elements — bit-identical, differential-testable."""
    return _int4_pays_off()


@functools.lru_cache(maxsize=1)
def int2_matmul_supported() -> bool:
    """One-time runtime probe: does this backend lower an int2 x int2 matmul
    with int32 accumulation?  Same discipline as int4_matmul_supported —
    XLA CPU rejects custom sub-byte element types outright, so the crumb
    mode emulates with int8 elements there (the widened WK grid is kept
    either way, which is what the CPU parity matrix exercises)."""
    if not hasattr(jnp, "int2"):
        return False
    try:
        a = jnp.ones((8, 8), jnp.int2)
        out = jax.lax.dot_general(a, a, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return bool(jax.device_get(out)[0, 0] == 8)
    except Exception:
        return False


@functools.lru_cache(maxsize=1)
def _int2_pays_off() -> bool:
    """Whether "auto" plane bits resolve to 2: the int2 matmul must lower
    AND the backend must have a hardware sub-byte MXU path worth taking —
    the same backend gate as _int8_pays_off / _int4_pays_off."""
    return jax.default_backend() == "tpu" and int2_matmul_supported()


def int2_elements_native() -> bool:
    """Whether jnp.int2 planes can actually live in VMEM on this backend.
    Where they cannot, the crumb-WK mode keeps its quadrupled K-step grid
    but unpacks to int8 elements — bit-identical, differential-testable."""
    return _int2_pays_off()


def resolved_plane_bits() -> int:
    """Plane width of the packed containment kernel (2, 4 or 8).

    Reads PLANE_BITS at call time (tests monkeypatch the module attribute);
    only the backend probes behind "auto" are cached.  Only meaningful when
    the resolved cooc dtype is int8 — the bf16 fallback keeps 16-bit
    planes."""
    if PLANE_BITS != "auto":
        return int(PLANE_BITS)
    if _int2_pays_off():
        return 2
    return 4 if _int4_pays_off() else 8


def resolved_kernel_dtype() -> str:
    """Unpack dtype of the packed Pallas containment kernel: the resolved
    cooc dtype, narrowed to "int4"/"int2" when a sub-byte plane mode is in
    effect.  The jnp planes fallback keeps the plain cooc dtype (XLA has no
    portable sub-byte contraction); all modes are exact and bit-identical."""
    dtype = resolved_cooc_dtype()
    if dtype == "int8":
        bits = resolved_plane_bits()
        if bits == 2:
            return "int2"
        if bits == 4:
            return "int4"
    return dtype


def emit_pipeline_enabled() -> bool:
    """Whether the packed containment kernel runs its explicit
    pltpu.emit_pipeline K-loop instead of the PR-6 "arbitrary"-dimension
    double buffering.  Reads EMIT_PIPELINE at call time (tests monkeypatch
    the module attribute); the availability probe behind both "auto" and
    the =1 force is cached.  Force still falls back where the probe fails
    (emit_pipeline cannot trace off TPU, even interpreted) — outputs are
    bit-identical either way, so the fallback is silent by design."""
    if EMIT_PIPELINE == "0":
        return False
    from . import pallas_kernels

    if not pallas_kernels.emit_pipeline_supported():
        return False
    if EMIT_PIPELINE == "1":
        return True
    return jax.default_backend() == "tpu"


def resolution_report() -> dict:
    """The single describe() surface for every kernel-mode decision: raw
    knob values next to what they resolved to (probes included), so
    plane-bits / emit-pipeline / fuse / block-skip choices are visible in
    one struct instead of three scattered gauges.  Published through the
    metrics shims into run stats ("kernel_resolution") and rendered on the
    shared --debug dense-plan line."""
    from . import pallas_kernels

    kernel_dtype = resolved_kernel_dtype()
    return {
        "cooc_dtype": resolved_cooc_dtype(),
        "plane_bits": resolved_plane_bits(),
        "kernel_dtype": kernel_dtype,
        "plane_elem": pallas_kernels._plane_elem(kernel_dtype),
        "emit_pipeline": emit_pipeline_enabled(),
        "fuse_verdict": fuse_verdict_enabled(),
        "block_skip": block_skip_enabled(),
        "knobs": {
            "RDFIND_COOC_DTYPE": COOC_DTYPE,
            "RDFIND_PLANE_BITS": PLANE_BITS,
            "RDFIND_EMIT_PIPELINE": EMIT_PIPELINE,
            "RDFIND_FUSE_VERDICT": FUSE_VERDICT,
            "RDFIND_BLOCK_SKIP": BLOCK_SKIP,
        },
    }


def fuse_verdict_enabled() -> bool:
    """Whether the dense CIND sweep runs the fused verdict kernel.  Reads
    FUSE_VERDICT at call time (tests monkeypatch the module attribute)."""
    if FUSE_VERDICT != "auto":
        return FUSE_VERDICT == "1"
    return jax.default_backend() == "tpu"


def block_skip_enabled() -> bool:
    """Whether the dense sweep schedules around all-zero membership blocks.
    Reads BLOCK_SKIP at call time (tests monkeypatch the module attribute)."""
    if BLOCK_SKIP != "auto":
        return BLOCK_SKIP == "1"
    return True


def round_up(n: int, mult: int) -> int:
    """Smallest multiple of `mult` >= max(n, 1)."""
    return -(-max(int(n), 1) // mult) * mult


def tile_for(c_pad: int, tile_max: int = DEFAULT_TILE) -> int:
    """Largest dep-tile width that divides `c_pad`, is a power-of-two
    multiple of CAP_MULT, and stays <= tile_max.

    Divisibility keeps every host-loop tile start exact under dynamic_slice's
    edge clamping (a clamped start would silently recompute earlier rows and
    emit duplicate pairs); the pow2 structure keeps tile widths MXU-friendly.
    """
    assert c_pad % CAP_MULT == 0, c_pad
    m = c_pad // CAP_MULT
    t = CAP_MULT * (m & -m)  # largest pow2 divisor of m, in columns
    return max(CAP_MULT, min(t, tile_max, c_pad))


def line_block_for(l_pad: int, cap: int = 1024) -> int:
    """K-step line-block granule of the fused sweep: the largest pow2
    multiple of LINE_MULT dividing `l_pad`, capped at `cap` rows (a block's
    two operand tiles then stay well inside VMEM); legacy pow2 plans below
    the row granule run as one block.  Divisibility keeps every block start
    exact — the same contract tile_for enforces on the capture axis."""
    if l_pad % LINE_MULT:
        return l_pad
    m = l_pad // LINE_MULT
    return min(LINE_MULT * (m & -m), cap)


def cap_pad(num_caps: int, mult: int = CAP_MULT) -> int:
    """Capture-axis padding under the active policy: tile-multiple (tight)
    when TILE_SCHEDULE is on, pow2-bucketed otherwise.  `mult` raises the
    granule (the sharded sketch path needs device-count divisibility)."""
    if TILE_SCHEDULE:
        return round_up(num_caps, mult)
    return round_up(max(CAP_MULT, segments.pow2_capacity(num_caps)), mult)


@dataclasses.dataclass(frozen=True)
class DensePlan:
    """Shape plan + tile schedule for the dense cooc path.

    The schedule (dep_tile_starts) enumerates the dep tiles that contain at
    least one real capture; all-padding tiles are never dispatched, and the
    occupancy accounting (real_flops / issued_flops) is what benches report
    as occupancy-corrected MFU instead of padded-FLOP MFU.
    """

    l_pad: int
    c_pad: int
    tile: int
    n_lines: int
    num_caps: int
    dtype: str
    # Raw-roofline rungs (ISSUE 6): resolved containment-kernel plane width,
    # whether the verdict sweep fuses (cooc counts stay in VMEM scratch),
    # the K-step line-block granule, and the data-driven block-skip record
    # (filled by discover_pairs_dense via dataclasses.replace once the
    # membership popcounts are known — shape planning alone cannot know it).
    plane_bits: int = 8
    fuse_verdict: bool = False
    line_block: int = 0
    n_blocks_skipped: int = 0

    def __iter__(self):  # legacy (l_pad, c_pad, tile) unpacking
        return iter((self.l_pad, self.c_pad, self.tile))

    @property
    def dep_tile_starts(self) -> tuple:
        """Dep-tile starts whose tile intersects [0, num_caps)."""
        return tuple(lo for lo in range(0, self.c_pad, self.tile)
                     if lo < self.num_caps)

    @property
    def n_tiles(self) -> int:
        return self.c_pad // self.tile

    @property
    def n_tiles_skipped(self) -> int:
        return self.n_tiles - len(self.dep_tile_starts)

    @property
    def n_line_blocks(self) -> int:
        return self.l_pad // self.line_block if self.line_block else 0

    @property
    def n_blocks(self) -> int:
        """(scheduled dep tile x line block) pairs the full-range sweep
        would visit — the denominator of the block-skip accounting."""
        return self.n_line_blocks * len(self.dep_tile_starts)

    @property
    def issued_flops(self) -> int:
        """MACs*2 actually dispatched by the scheduled tile sweep."""
        return 2 * self.l_pad * self.c_pad * self.tile \
            * len(self.dep_tile_starts)

    @property
    def real_flops(self) -> int:
        """MACs*2 the unpadded workload needs."""
        return 2 * self.n_lines * self.num_caps * self.num_caps

    @property
    def occupancy(self) -> float:
        return self.real_flops / max(self.issued_flops, 1)

    def describe(self) -> dict:
        """Occupancy record for run stats / --debug / bench JSON."""
        return {
            "policy": "tile" if TILE_SCHEDULE else "pow2",
            "dtype": self.dtype,
            "plane_bits": self.plane_bits,
            "fuse_verdict": self.fuse_verdict,
            "l_real": self.n_lines, "l_pad": self.l_pad,
            "c_real": self.num_caps, "c_pad": self.c_pad,
            "tile": self.tile,
            "n_tiles": self.n_tiles,
            "n_tiles_skipped": self.n_tiles_skipped,
            "line_block": self.line_block,
            "n_blocks": self.n_blocks,
            "n_blocks_skipped": self.n_blocks_skipped,
            "issued_flops": self.issued_flops,
            "real_flops": self.real_flops,
            "occupancy": round(self.occupancy, 4),
        }


def cooc_dot(a, b, dims=((0,), (0,))):
    """Exact integer counts from a 0/1-matrix product: accumulate in the
    dtype-matched exact accumulator (f32 for bf16, int32 for int8)."""
    acc = jnp.int32 if a.dtype == jnp.int8 else jnp.float32
    return jax.lax.dot_general(
        a, b, (dims, ((), ())), preferred_element_type=acc).astype(jnp.int32)


def pack_bool(x):
    """(R, C) bool/0-1 -> (R, ceil(C/32)) uint32, little bit order per word.

    The single packing implementation shared by every device stage; the host
    inverse is unpack_cind_bits (np.unpackbits bitorder="little").
    """
    r, c = x.shape
    if c % 32:
        x = jnp.pad(x, ((0, 0), (0, 32 - c % 32)))
        c = x.shape[1]
    lanes = x.astype(jnp.uint32).reshape(r, c // 32, 32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return (lanes * weights[None, None, :]).sum(axis=2, dtype=jnp.uint32)


def dense_plan(n_lines: int, num_caps: int, tile: int = DEFAULT_TILE):
    """DensePlan for the dense path, or None when it does not fit.

    c_pad is always a multiple of CAP_MULT=128 (MXU lanes and 32-bit packing)
    and of the tile (exact dep-tile starts under dynamic_slice clamping).
    Under the default tile-multiple policy l_pad/c_pad hug the real shape
    (occupancy > 0.9 on non-degenerate workloads); RDFIND_TILE_SCHEDULE=0
    restores the legacy pow2 buckets, whose worst case issues ~2x the rows
    and ~2x the columns (the headline workload measured ~56% row occupancy).
    """
    if n_lines == 0 or num_caps == 0:
        return None
    dtype = resolved_cooc_dtype()
    if dtype != "int8" and n_lines >= MAX_LINES_EXACT_F32:
        return None  # int8 accumulates in int32: exact to 2^31 counts
    if TILE_SCHEDULE:
        l_pad = round_up(n_lines, LINE_MULT)
        c_pad = cap_pad(num_caps)
        tile = tile_for(c_pad, tile)
    else:
        # Legacy pow2 buckets: maximal compiled-program reuse across datasets
        # (segments.pow2_capacity); c_pad a pow2 >= 128 is automatically a
        # multiple of the (pow2) tile.
        l_pad = max(8, segments.pow2_capacity(n_lines))
        c_pad = cap_pad(num_caps)
        tile = min(tile, c_pad)
    elem_bytes = 1 if dtype == "int8" else 2
    if l_pad * c_pad * elem_bytes > DENSE_M_BUDGET_BYTES:
        return None
    return DensePlan(l_pad=l_pad, c_pad=c_pad, tile=tile, n_lines=n_lines,
                     num_caps=num_caps, dtype=dtype,
                     plane_bits=resolved_plane_bits(),
                     fuse_verdict=fuse_verdict_enabled(),
                     line_block=line_block_for(l_pad))


@functools.partial(jax.jit, static_argnames=("l_pad", "c_pad", "dtype"))
def _build_membership(line_gid, line_cap, valid, *, l_pad: int, c_pad: int,
                      dtype: str):
    dt = jnp.int8 if dtype == "int8" else jnp.bfloat16
    li = jnp.where(valid, line_gid, l_pad)
    ci = jnp.where(valid, line_cap, c_pad)
    m = jnp.zeros((l_pad, c_pad), dt)
    return m.at[li, ci].set(jnp.asarray(1, dt), mode="drop")


def build_membership(line_gid, line_cap, valid, *, l_pad: int, c_pad: int,
                     dtype: str | None = None):
    """Scatter (line, capture) rows into the (l_pad, c_pad) 0/1 matrix.

    The element type (resolved_cooc_dtype() by default; `dtype` overrides)
    is a STATIC jit key: the inputs' avals don't carry it, so it must key the
    cache explicitly or a dtype flip would silently reuse the other mode's
    compiled program.  Downstream consumers take `m` itself, whose aval
    re-keys them."""
    return _build_membership(line_gid, line_cap, valid, l_pad=l_pad,
                             c_pad=c_pad,
                             dtype=resolved_cooc_dtype() if dtype is None
                             else dtype)


@functools.partial(jax.jit, static_argnames=("tile",))
def cooc_cind_tile(m, dep_lo, dep_count, cap_code, cap_v1, cap_v2,
                   min_support, *, tile: int):
    """One (tile x C_pad) block of the CIND matrix, bit-packed along refs.

    m: (l_pad, c_pad) membership; dep_lo: first dep capture id of this tile;
    dep_count/cap_*: (c_pad,) per-capture support and identity columns.
    Returns (tile, c_pad // 32) uint32 where bit r of word w in row d means
    "capture dep_lo+d is CIND-included in capture 32w+r".

    The elementwise masks mirror _stage_merge (models/allatonce.py): support
    test, min_support, no self-pairs, and the trivially-implied-pair rule of
    data/Condition.scala:35-43.
    """
    c_pad = m.shape[1]
    m_tile = jax.lax.dynamic_slice(m, (0, dep_lo), (m.shape[0], tile))
    cooc = cooc_dot(m_tile, m)

    d_idx = dep_lo + jnp.arange(tile, dtype=jnp.int32)
    d_safe = jnp.clip(d_idx, 0, c_pad - 1)
    support = dep_count[d_safe][:, None]
    is_cind = (cooc == support) & (support >= min_support)
    is_cind &= d_idx[:, None] != jnp.arange(c_pad, dtype=jnp.int32)[None, :]

    d_code = cap_code[d_safe][:, None]
    d_v1 = cap_v1[d_safe][:, None]
    d_v2 = cap_v2[d_safe][:, None]
    r_code = cap_code[None, :]
    implied = cc.is_subcode(r_code, d_code) & jnp.where(
        cc.first_subcapture(d_code) == r_code,
        cap_v1[None, :] == d_v1, cap_v1[None, :] == d_v2)
    return pack_bool(is_cind & ~implied)


@functools.partial(jax.jit, static_argnames=("kl", "tile"))
def _stage_block_counts(m, *, kl: int, tile: int):
    """(l_pad//kl, c_pad//tile) int32 membership popcounts per
    (line-block x dep-tile) pair — the skew record driving the sub-tile
    skip schedule (the same per-line popcounts the join-line rebalancer
    reads for skew, here reduced at block granularity on device)."""
    l_pad, c_pad = m.shape
    acc = jnp.int32 if m.dtype == jnp.int8 else jnp.float32
    blocks = m.reshape(l_pad // kl, kl, c_pad // tile, tile)
    return blocks.sum(axis=(1, 3), dtype=acc).astype(jnp.int32)


def _fused_ref_chunk(c_pad: int, cap: int = 16384) -> int:
    """Ref-axis chunk of one fused kernel dispatch: bounds the transient
    uint8 verdict block (tile x chunk) while the packed output stays
    c_pad/8 bytes per row.  Divides c_pad by the tile_for contract."""
    return tile_for(c_pad, cap)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _fused_cind_tile(m, dep_lo, dep_count, cap_code, cap_v1, cap_v2,
                     min_support, block_ids, n_real, *, tile: int,
                     interpret: bool):
    """One (tile x c_pad) CIND block via the fused Pallas kernel.

    Same packed-bitmap contract as cooc_cind_tile, computed without ever
    writing the int32 cooc count matrix to HBM: the kernel accumulates each
    (128 x 128) count block in VMEM scratch and emits the verdict (CIND
    test + support filter + diagonal + trivially-implied mask, the
    _stage_merge semantics) plus the per-dep referenced-set popcount.  The
    K (line) dimension walks only the scalar-prefetched `block_ids`
    (padded entries are compute-guarded), which is where the sub-tile
    sparsity skip happens.  Returns (packed, popc, count): popc is the
    (tile, 1) per-dep CIND count the minimality/extraction stages size
    with, count its scalar sum — callers skip the separate packed_count
    dispatch over the bitmap.
    """
    from . import pallas_kernels

    c_pad = m.shape[1]
    rc = _fused_ref_chunk(c_pad)
    dep_count = jnp.asarray(dep_count, jnp.int32)
    code32 = jnp.asarray(cap_code, jnp.int32)
    v1_32 = jnp.asarray(cap_v1, jnp.int32)
    v2_32 = jnp.asarray(cap_v2, jnp.int32)

    m_tile = jax.lax.dynamic_slice(m, (0, dep_lo), (m.shape[0], tile))
    col = lambda a: jax.lax.dynamic_slice(a, (dep_lo,), (tile,)) \
        .reshape(tile, 1)
    sup_col = col(dep_count)
    ok_col = (sup_col >= jnp.int32(min_support)).astype(jnp.int32)
    gid_col = dep_lo + jnp.arange(tile, dtype=jnp.int32).reshape(tile, 1)
    ridx = jnp.arange(c_pad, dtype=jnp.int32).reshape(1, c_pad)

    packed_chunks, popc = [], None
    for rlo in range(0, c_pad, rc):
        verdict, pc = pallas_kernels.fused_cind_blocks(
            m_tile, m, sup_col, ok_col, gid_col, col(code32), col(v1_32),
            col(v2_32), ridx, code32.reshape(1, c_pad),
            v1_32.reshape(1, c_pad), block_ids, n_real, ref_lo=rlo,
            ref_chunk=rc, interpret=interpret)
        packed_chunks.append(pack_bool(verdict))
        popc = pc if popc is None else popc + pc
    packed = (packed_chunks[0] if len(packed_chunks) == 1
              else jnp.concatenate(packed_chunks, axis=1))
    return packed, popc, popc.sum(dtype=jnp.int32)


def _inbounds(packed, rows, cols):
    """Zero out words outside the [0, rows) x [0, cols) bit region.

    rows/cols are TRACED operands (not static jit keys): the compiled
    programs key only on the pow2-bucketed packed shape, preserving the
    repo's program-reuse policy across lattice levels and datasets."""
    word_idx = jnp.arange(packed.shape[1], dtype=jnp.int32)
    partial = jnp.clip(cols - word_idx * 32, 0, 32)
    # Shift stays in [0, 31]: uint32 << 32 is implementation-defined in XLA,
    # so the partial == 32 case selects the full mask without ever evaluating
    # an out-of-range shift (even in an unselected where branch).
    low = (jnp.uint32(1) << jnp.minimum(partial, 31).astype(jnp.uint32)) \
        - jnp.uint32(1)
    col_mask = jnp.where(partial >= 32, jnp.uint32(0xFFFFFFFF), low)
    row_ok = jnp.arange(packed.shape[0], dtype=jnp.int32) < rows
    return jnp.where(row_ok[:, None], packed & col_mask[None, :], 0)


@jax.jit
def packed_count(packed, rows, cols):
    """Set bits in the in-bounds region; int32 is exact under the
    EXTRACT_DEVICE_ELEMS <= 2^28-bit gate callers apply."""
    return jax.lax.population_count(_inbounds(packed, rows, cols)).sum(
        dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("cap",))
def packed_nonzero(packed, rows, cols, *, cap: int):
    """(row, col) indices of the first `cap` in-bounds set bits (row-major)."""
    from . import sketch

    d, r = jnp.nonzero(sketch.unpack_planes(_inbounds(packed, rows, cols)),
                       size=cap, fill_value=0)
    return d.astype(jnp.int32), r.astype(jnp.int32)


# Device extraction materializes the unpacked relation plus nonzero's scan
# intermediates; past this element count a relation decodes in row strips so
# each strip's intermediates stay under the bound.  2^28 bits also keeps
# packed_count's int32 sum exact.
EXTRACT_DEVICE_ELEMS = 1 << 28

# Device bytes pinned by pending sized-nonzero outputs before a batched pull
# flush.  Without it, near-dense relations could pin index pairs proportional
# to the total set-bit count (32 GB for a saturated 4096 x 2^20-bit sweep)
# while waiting for one giant device_get.
PULL_BYTES_BUDGET = 1 << 28


def _flush_pulls(pend):
    """One batched device_get of pending sized-nonzero outputs.

    pend: list of (key, count, (d_dev, r_dev)).  Returns [(key, d, r)] with
    host int64 arrays truncated to their exact counts."""
    flat = iter(jax.device_get([x for _, _, dr in pend for x in dr]))
    out = []
    for key, c, _ in pend:
        d, r = next(flat), next(flat)
        out.append((key, d[:c].astype(np.int64), r[:c].astype(np.int64)))
    return out


def extract_packed(packed, rows: int, cols: int):
    """Decode a packed bool relation -> host (row, col) int64 index arrays.

    Small enough relations decode on device — an exact popcount dispatch,
    then a sized nonzero — so the host pulls one scalar plus exactly the
    set-bit index pairs, never the bit matrix itself (the multi-MB pull +
    host unpackbits scan dominated the lattice's non-matmul wall clock over
    the tunnel).  Oversized relations decode in row strips: each strip's
    unpacked planes + nonzero intermediates stay <= EXTRACT_DEVICE_ELEMS
    bits on device, counts and index pulls are batched so the host syncs
    twice total, and the bit matrix still never crosses the tunnel (the r4
    host fallback pulled C^2/32 bytes per oversized tile — strategy 2's
    second measured bottleneck)."""
    words = packed.shape[1]
    total_bits = packed.shape[0] * words * 32
    if total_bits <= EXTRACT_DEVICE_ELEMS:
        return extract_packed_iter([lambda: (packed, rows, cols)],
                                   total_bits)[0]
    # Strips are just same-shaped small tiles decoded through the shared
    # batched iterator; a partial final strip compiles its own (smaller)
    # program, which the iterator's per-shape thunks already allow (the
    # tile-multiple c_pad policy means words need not be pow2).  tile_bits
    # is clamped for the pathological one-row-over-budget shape
    # (words*32 > EXTRACT_DEVICE_ELEMS), where a single row must decode in
    # one shot anyway and clamping avoids bouncing back into this strip path.
    h = max(1, EXTRACT_DEVICE_ELEMS // (words * 32))
    los = list(range(0, min(rows, packed.shape[0]), h))

    def make(lo):
        return lambda: (packed[lo:lo + h], min(rows - lo, h), cols)

    pairs = extract_packed_iter([make(lo) for lo in los],
                                min(h * words * 32, EXTRACT_DEVICE_ELEMS))
    out_d = [d + lo for lo, (d, _) in zip(los, pairs) if d.size]
    out_r = [r for _, (d, r) in zip(los, pairs) if d.size]
    if not out_d:
        z = np.zeros(0, np.int64)
        return z, z
    return np.concatenate(out_d), np.concatenate(out_r)


def extract_packed_iter(thunks, tile_bits: int):
    """Decode a stream of packed tiles with batched, pipelined host syncs.

    thunks: callables dispatching one tile each, returning (packed, rows,
    cols).  Tiles MAY differ in shape (small_to_large batches its mixed
    lattice relations through one call); `tile_bits` must be an UPPER BOUND
    on any tile's packed bits — it is what bounds how many tiles sit on
    device awaiting decode (EXTRACT_DEVICE_ELEMS per batch), so an
    underestimate breaks the residency math (ADVICE r5).  Each batch costs
    one counts sync; index pulls flush under PULL_BYTES_BUDGET.

    Pipelined schedule (unless RDFIND_SYNC_PASSES forces the serial one):
    batch i+1's tiles are dispatched BEFORE batch i's counts are pulled, so
    tile compute overlaps the count readback and index pulls; the batch size
    is halved to keep the two-batches-in-flight residency inside the same
    EXTRACT_DEVICE_ELEMS budget.  Oversized tiles fall through to
    extract_packed's strip decode.  Returns [(d, r)] host int64 arrays in
    thunk order — the shared decode behind the dense strategy-0 sweep and
    strategy 2's candidate generation.
    """
    if tile_bits > EXTRACT_DEVICE_ELEMS:
        return [extract_packed(*t()[:3]) for t in thunks]
    out = [None] * len(thunks)
    pipelined = not dispatch.sync_passes_forced() and len(thunks) > 1
    batch = max(1, EXTRACT_DEVICE_ELEMS // tile_bits // (2 if pipelined
                                                         else 1))
    empty = (np.zeros(0, np.int64), np.zeros(0, np.int64))

    def launch(lo):
        # A thunk may return a 4th element: the tile's set-bit count already
        # computed on device (the fused kernel's per-dep popcount summed),
        # which replaces the separate packed_count pass over the bitmap.
        group, counts = [], []
        for j, t in enumerate(thunks[lo:lo + batch]):
            res = t()
            p, r, c = res[:3]
            group.append((lo + j, p, r, c))
            counts.append(res[3] if len(res) > 3 else
                          packed_count(p, jnp.int32(r), jnp.int32(c)))
        dispatch.stage_to_host(counts)
        return group, counts

    def drain_batch(group, counts):
        counts = jax.device_get(counts)
        pend, pend_bytes = [], 0

        def drain():
            nonlocal pend, pend_bytes
            for k, d, r in _flush_pulls(pend):
                out[k] = (d, r)
            pend, pend_bytes = [], 0

        for n, (k, p, rows, cols) in zip(counts, group):
            n = int(n)
            if not n:
                out[k] = empty
                continue
            cap = segments.pow2_capacity(n)
            pend.append((k, n, packed_nonzero(p, jnp.int32(rows),
                                              jnp.int32(cols), cap=cap)))
            dispatch.stage_to_host(pend[-1][2])
            pend_bytes += 8 * cap
            if pend_bytes >= PULL_BYTES_BUDGET:
                drain()
        drain()

    prev = None
    for lo in range(0, len(thunks), batch):
        cur = launch(lo)
        if not pipelined:
            drain_batch(*cur)
            continue
        if prev is not None:
            drain_batch(*prev)
        prev = cur
    if prev is not None:
        drain_batch(*prev)
    return out


def unpack_cind_bits(packed: np.ndarray, c_pad: int) -> np.ndarray:
    """(tile, c_pad//32) uint32 -> (tile, c_pad) 0/1 uint8 on host."""
    return np.unpackbits(
        np.ascontiguousarray(packed).view(np.uint8),
        axis=1, bitorder="little")[:, :c_pad]


def discover_pairs_dense(m, dep_count, cap_code, cap_v1, cap_v2, min_support,
                         num_caps: int, tile: int, starts=None, plan=None,
                         stats=None):
    """Run the tiled cooc pass; return (dep_id, ref_id, support) numpy arrays.

    m: (l_pad, c_pad) device membership matrix.  The host loops over the
    scheduled dep tiles (`starts`, default: every tile intersecting
    [0, num_caps) — all-padding tiles are never dispatched) sending the
    packed CIND blocks, then decodes them on device: one batched pull of all
    tile popcounts, one batched pull of the sized nonzeros — only the
    set-bit index pairs ever reach the host (same two-phase decode as
    extract_packed, batched across tiles).

    Under the fused-verdict policy (`plan.fuse_verdict` /
    fuse_verdict_enabled) each tile runs the fused Pallas kernel instead of
    the materialized cooc_cind_tile, and its in-kernel popcount replaces the
    packed_count dispatch.  With block skipping on, per-(dep-tile x
    line-block) membership popcounts prune the schedule first: dep tiles
    whose captures occur in no line are dropped outright (both backends),
    and the fused kernel's K steps visit only each tile's nonzero line
    blocks.  `stats` (via the obs shims) records the skip accounting into
    the dense_plan struct.
    """
    import math

    l_pad, c_pad = m.shape
    dep_count_d = jnp.asarray(dep_count, jnp.int32)
    code_d = jnp.asarray(cap_code, jnp.int32)
    v1_d = jnp.asarray(cap_v1, jnp.int32)
    v2_d = jnp.asarray(cap_v2, jnp.int32)
    ms = jnp.int32(min_support)

    los = list(starts) if starts is not None else list(range(0, num_caps,
                                                             tile))

    kl = (plan.line_block if plan is not None and plan.line_block
          else line_block_for(l_pad))
    n_line_blocks = l_pad // kl
    from . import pallas_kernels

    fused = (plan.fuse_verdict if plan is not None else fuse_verdict_enabled())
    fused = fused and tile % 128 == 0 and c_pad % 128 == 0 \
        and l_pad % kl == 0 and l_pad % 8 == 0 \
        and pallas_kernels.scalar_prefetch_available()
    interp = jax.default_backend() != "tpu"

    # Sub-tile skip schedule: one small device reduction + host pull of the
    # (n_line_blocks x n_tiles) popcount grid, amortized against the sweep.
    block_counts = None
    if block_skip_enabled() and l_pad % kl == 0 and c_pad % tile == 0 \
            and (n_line_blocks > 1 or len(los) > 1):
        block_counts = np.asarray(_stage_block_counts(m, kl=kl, tile=tile))
    n_blocks_total = n_line_blocks * len(los)
    n_blocks_skipped = n_tiles_data_skipped = 0
    tile_blocks = {}
    if block_counts is not None:
        kept = []
        for lo in los:
            col = block_counts[:, lo // tile]
            nz = np.flatnonzero(col).astype(np.int32)
            if nz.size == 0:
                # All-zero dep tile: its captures occur in no line, so no
                # verdict bit can set — drop it from the schedule entirely.
                n_tiles_data_skipped += 1
                n_blocks_skipped += n_line_blocks
                continue
            kept.append(lo)
            if fused:
                tile_blocks[lo] = nz
                n_blocks_skipped += n_line_blocks - nz.size
        los = kept
    if stats is not None:
        from ..obs import datastats, metrics
        metrics.gauge_set(stats, "n_blocks_skipped", n_blocks_skipped)
        metrics.struct_update(stats, "dense_plan",
                              n_blocks_skipped=n_blocks_skipped,
                              n_tiles_data_skipped=n_tiles_data_skipped)
        if datastats.enabled():
            datastats.publish_block_skip(stats, n_blocks=n_blocks_total,
                                         n_blocks_skipped=n_blocks_skipped)

    def make(lo):
        return lambda: (cooc_cind_tile(m, jnp.int32(lo), dep_count_d, code_d,
                                       v1_d, v2_d, ms, tile=tile),
                        min(num_caps - lo, tile), num_caps)

    def make_fused(lo):
        nz = tile_blocks.get(lo)
        if nz is None:
            nz = np.arange(n_line_blocks, dtype=np.int32)
        # Bucket the K grid to a pow2 so retraces stay logarithmic in the
        # block count; padded steps fetch block 0 and are compute-guarded.
        bucket = 1 << max(0, math.ceil(math.log2(nz.size)))
        bids = jnp.asarray(np.pad(nz, (0, bucket - nz.size)))
        nr = jnp.asarray(np.full(1, nz.size, np.int32))

        def thunk():
            packed, _, count = _fused_cind_tile(
                m, jnp.int32(lo), dep_count_d, code_d, v1_d, v2_d, ms,
                bids, nr, tile=tile, interpret=interp)
            return packed, min(num_caps - lo, tile), num_caps, count

        return thunk

    pairs = extract_packed_iter(
        [(make_fused if fused else make)(lo) for lo in los], tile * c_pad)
    deps = [d + lo for lo, (d, _) in zip(los, pairs) if d.size]
    refs = [r for _, (d, r) in zip(los, pairs) if d.size]
    dep_id = np.concatenate(deps) if deps else np.zeros(0, np.int64)
    ref_id = np.concatenate(refs) if refs else np.zeros(0, np.int64)
    support = np.asarray(dep_count)[dep_id] if dep_id.size else np.zeros(0, np.int64)
    return dep_id, ref_id, support
