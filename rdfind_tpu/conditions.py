"""The 6-bit capture/condition code algebra.

A *capture* is a projection of one RDF triple field under an equality condition on one
or two of the other fields, e.g. ``o[p=birthPlace]`` ("all objects of triples whose
predicate is birthPlace").  Capture codes pack this shape into 6 bits:

  * low 3 bits ("primary conditions"): which fields carry the equality condition
    (subject=1, predicate=2, object=4);
  * high 3 bits ("secondary conditions"): which field is projected.

A standard capture has 1 or 2 primary bits, exactly 1 secondary bit, and the two sets
are disjoint.

Semantics follow the reference's ``ConditionCodes`` object
(/root/reference/rdfind-algorithm/src/main/scala/de/hpi/isg/sodap/rdfind/util/
ConditionCodes.scala:12-129), re-expressed as branch-free integer arithmetic so every
function works elementwise on numpy/jax arrays as well as on Python ints — these run
inside jitted TPU kernels.
"""

SUBJECT = 1
PREDICATE = 2
OBJECT = 4
NUM_TYPE_BITS = 3
TYPE_MASK = 7

SUBJECT_PREDICATE = SUBJECT | PREDICATE
SUBJECT_OBJECT = SUBJECT | OBJECT
PREDICATE_OBJECT = PREDICATE | OBJECT

_CODE_TO_CHAR = {SUBJECT: "s", PREDICATE: "p", OBJECT: "o"}


def merge(code1, code2):
    return code1 | code2


def primary(code):
    """The condition-field bits of a code."""
    return code & TYPE_MASK


def secondary(code):
    """The projection-field bits of a code."""
    return (code >> NUM_TYPE_BITS) & TYPE_MASK


def add_secondary(code):
    """Set as secondary (projection) all fields that are not primary conditions."""
    return (code & TYPE_MASK) | ((~code & TYPE_MASK) << NUM_TYPE_BITS)


def lowest_bit(x):
    """Lowest set bit of x (0 if x == 0).  Branch-free, array-safe."""
    return x & (-x)


def popcount3(x):
    """Number of set bits among the low 3 bits.  Array-safe."""
    return (x & 1) + ((x >> 1) & 1) + ((x >> 2) & 1)


def add_first_secondary(code):
    """Use the lowest unused field as the projection."""
    unused = TYPE_MASK ^ (code & TYPE_MASK)
    return create(primary(code), secondary_condition=lowest_bit(unused))


def add_second_secondary(code):
    """Use the second-lowest unused field as the projection."""
    unused = TYPE_MASK ^ (code & TYPE_MASK)
    first = lowest_bit(unused)
    return create(primary(code), secondary_condition=unused & ~first)


def decode(code):
    """Split a code's primary bits into (first, second, free) single-bit codes.

    ``first``/``second`` are the two lowest set bits (second is 0 for unary codes);
    ``free`` is the remaining field(s).
    """
    first = lowest_bit(code & TYPE_MASK)
    second = lowest_bit((code & TYPE_MASK) & ~first)
    free = ~first & ~second & TYPE_MASK
    return first, second, free


def create(first_primary, second_primary=0, secondary_condition=0):
    return ((first_primary | second_primary) & TYPE_MASK) | (
        (secondary_condition & TYPE_MASK) << NUM_TYPE_BITS
    )


def is_subcode(candidate, super_code):
    return (candidate & super_code) == candidate


def is_binary(code):
    """True when the code has exactly 2 condition fields.  Array-safe."""
    return popcount3(code & TYPE_MASK) == 2


def is_unary(code):
    """True when the code has exactly 1 condition field.  Array-safe."""
    return popcount3(code & TYPE_MASK) == 1


def remove_primary(capture_code):
    return capture_code & ~TYPE_MASK


def first_subcapture(capture_code):
    """Unary capture code keeping only the lowest condition field (same projection)."""
    return remove_primary(capture_code) | lowest_bit(capture_code & TYPE_MASK)


def second_subcapture(capture_code):
    """Unary capture code keeping only the second condition field (same projection)."""
    first = lowest_bit(capture_code & TYPE_MASK)
    return remove_primary(capture_code) | lowest_bit((capture_code & TYPE_MASK) & ~first)


def is_valid_standard_capture(code):
    """1-or-2 primary bits, exactly 1 secondary bit, disjoint, nothing above bit 5.

    Array-safe (returns a boolean array for array input).
    """
    prim = primary(code)
    sec = secondary(code)
    n_prim = popcount3(prim)
    ok_prim = (n_prim >= 1) & (n_prim <= 2)
    ok_sec = popcount3(sec) == 1
    disjoint = (prim & sec) == 0
    clean = (code & ~0x3F) == 0
    return ok_prim & ok_sec & disjoint & clean


# The 9 valid standard capture codes: 3 projections x 2 unary conditions (6 codes)
# + 3 projections x 1 binary condition (3 codes).
ALL_VALID_CAPTURE_CODES = tuple(c for c in range(64) if is_valid_standard_capture(c))

# Unary condition codes paired with "their" field for frequency mining: the 3 fields.
FIELD_CODES = (SUBJECT, PREDICATE, OBJECT)
# Field index (0=s, 1=p, 2=o) for each single-bit code.
FIELD_INDEX = {SUBJECT: 0, PREDICATE: 1, OBJECT: 2}


def pretty(capture_code, value1, value2=None):
    """Human-readable capture, e.g. ``o[s=x,p=y]``.

    Matches the reference's pretty printer (ConditionCodes.scala:102-107).
    """
    proj = _CODE_TO_CHAR.get(secondary(capture_code), "")
    first, second, _ = decode(primary(capture_code))
    if second == 0:
        return f"{proj}[{_CODE_TO_CHAR[first]}={value1}]"
    return f"{proj}[{_CODE_TO_CHAR[first]}={value1},{_CODE_TO_CHAR[second]}={value2}]"
