"""Pure-Python oracles for CIND discovery — the golden reference for every kernel.

Two independent implementations with identical outputs:

* `discover_cinds_definitional` — brute force straight from the CIND definition:
  enumerate every capture's extension set, test pairwise containment.  Slow, obviously
  correct, mechanism-free.

* `discover_cinds_joinline` — mirrors the reference's dataflow mechanics
  (join-partner emission with frequency pruning -> join lines -> per-line evidences ->
  refset intersection; rdfind-algorithm/.../operators/CreateJoinPartners.scala:86-147,
  CreateAllCindCandidates.scala:106-121, IntersectCindCandidates.scala:14-51), with
  dict-of-sets instead of Flink shuffles.

Every device pipeline is golden-tested against these on random datasets.

Triples are (s, p, o) tuples of hashable values (strings or interned ints).
"""

from __future__ import annotations

from collections import Counter, defaultdict

from . import conditions as cc
from .data import NO_VALUE

_FIELD_BITS = (cc.SUBJECT, cc.PREDICATE, cc.OBJECT)


def capture_extensions(triples, projections="spo"):
    """Map capture (code, v1, v2) -> set of projected values.

    Unary captures use v2 = NO_VALUE.  Only captures whose projection field is in
    `projections` exist (the reference's --projection-attributes flag,
    RDFind.scala:639-721).
    """
    ext = defaultdict(set)
    proj_bits = [b for ch, b in zip("spo", _FIELD_BITS) if ch in projections]
    for t in triples:
        for proj_bit in proj_bits:
            pi = cc.FIELD_INDEX[proj_bit]
            proj_val = t[pi]
            others = [i for i in range(3) if i != pi]
            a, b = others  # field indices in ascending bit order
            bit_a, bit_b = _FIELD_BITS[a], _FIELD_BITS[b]
            ext[(cc.create(bit_a, secondary_condition=proj_bit), t[a], NO_VALUE)].add(proj_val)
            ext[(cc.create(bit_b, secondary_condition=proj_bit), t[b], NO_VALUE)].add(proj_val)
            ext[(cc.create(bit_a, bit_b, proj_bit), t[a], t[b])].add(proj_val)
    return ext


def _implies(dep, ref):
    """dep implies ref: ref is dep itself or a value-matching subcapture of dep.

    Reference: data/Condition.scala:35-43 (isImpliedBy, with roles swapped).

    Inherited quirk, kept for output parity: for two *distinct* binary captures with
    the same code, the subcode test degenerates to ``ref_v1 == dep_v2``, so e.g.
    p[s=x,o=y] vs p[s=y,o=z] is (wrongly, per the pure definition) treated as implied
    and the pair is suppressed.  The reference behaves identically, so "identical
    output" requires mirroring it (pinned by test_implies_equal_code_quirk).
    """
    if dep == ref:
        return True
    dep_code, dep_v1, dep_v2 = dep
    ref_code, ref_v1, ref_v2 = ref
    if not cc.is_subcode(ref_code, dep_code):
        return False
    if cc.first_subcapture(dep_code) == ref_code:
        return ref_v1 == dep_v1
    return ref_v1 == dep_v2


def discover_cinds_definitional(triples, min_support, projections="spo"):
    """All CINDs (dep, ref, support) by definition.

    A CIND dep ⊆ ref holds when ext(dep) ⊆ ext(ref), |ext(dep)| >= min_support and
    dep does not trivially imply ref.  Returns a set of 7-tuples
    (dep_code, dep_v1, dep_v2, ref_code, ref_v1, ref_v2, support).
    """
    ext = capture_extensions(triples, projections)
    items = list(ext.items())
    out = set()
    for dep, dep_ext in items:
        support = len(dep_ext)
        if support < min_support:
            continue
        for ref, ref_ext in items:
            if _implies(dep, ref):
                continue
            if dep_ext <= ref_ext:
                out.add((*dep, *ref, support))
    return out


def discover_cinds_joinline(triples, min_support, projections="spo",
                            use_frequent_condition_filter=True,
                            use_association_rules=False):
    """All CINDs via the reference's join-line mechanics.

    Without association rules, output must equal `discover_cinds_definitional` — the
    frequency filters are pure pruning (a referenced capture of a valid CIND is at
    least as large as the dependent, hence frequent).  With use_association_rules
    (requires the frequency filter, as in the reference where ARs are mined from the
    frequent-item sets), AR-implied binary captures are suppressed and AR-restating
    1/1 pairs removed (CreateJoinPartners.scala:100-146,
    CreateDependencyCandidates.scala:125-130).
    """
    # -- Frequent-condition mining (FrequentConditionPlanner.scala:291-311,374-394).
    if use_frequent_condition_filter:
        unary_counts = Counter()
        binary_counts = Counter()
        for s, p, o in triples:
            t = (s, p, o)
            for i in range(3):
                unary_counts[(_FIELD_BITS[i], t[i])] += 1
            for a, b in ((0, 1), (0, 2), (1, 2)):
                binary_counts[(_FIELD_BITS[a] | _FIELD_BITS[b], t[a], t[b])] += 1
        unary_freq = {k for k, v in unary_counts.items() if v >= min_support}
        binary_freq = {k for k, v in binary_counts.items() if v >= min_support}
        rules = set()
        if use_association_rules:
            # Perfect-confidence rules over frequent conditions:
            # (a=va) -> (b=vb) iff count(a=va ∧ b=vb) == count(a=va) >= min_support.
            for (code, va, vb), cab in binary_counts.items():
                if cab < min_support:
                    continue
                bits = [b for b in _FIELD_BITS if code & b]
                for (ba, bb, x, y) in ((bits[0], bits[1], va, vb),
                                       (bits[1], bits[0], vb, va)):
                    if cab == unary_counts[(ba, x)]:
                        rules.add((ba, bb, x, y))

        def u_ok(bit, val):
            return (bit, val) in unary_freq

        def b_ok(code, va, vb):
            return (code, va, vb) in binary_freq
    else:
        rules = set()

        def u_ok(bit, val):
            return True

        def b_ok(code, va, vb):
            return True

    # -- Join-partner emission (CreateJoinPartners.scala:86-147).  The reference
    # suppresses one unary partner when the binary partner is emitted and re-splits
    # binary captures at consumption (CreateDependencyCandidates.scala:90-105); always
    # emitting both unaries + dedup yields the same join-line capture sets.
    proj_bits = [b for ch, b in zip("spo", _FIELD_BITS) if ch in projections]
    join_lines = defaultdict(set)
    for t in triples:
        for proj_bit in proj_bits:
            pi = cc.FIELD_INDEX[proj_bit]
            join_val = t[pi]
            a, b = [i for i in range(3) if i != pi]
            bit_a, bit_b = _FIELD_BITS[a], _FIELD_BITS[b]
            if u_ok(bit_a, t[a]):
                join_lines[join_val].add(
                    (cc.create(bit_a, secondary_condition=proj_bit), t[a], NO_VALUE))
            if u_ok(bit_b, t[b]):
                join_lines[join_val].add(
                    (cc.create(bit_b, secondary_condition=proj_bit), t[b], NO_VALUE))
            ar_implied = ((bit_a, bit_b, t[a], t[b]) in rules
                          or (bit_b, bit_a, t[b], t[a]) in rules)
            if (u_ok(bit_a, t[a]) and u_ok(bit_b, t[b])
                    and b_ok(bit_a | bit_b, t[a], t[b]) and not ar_implied):
                join_lines[join_val].add((cc.create(bit_a, bit_b, proj_bit), t[a], t[b]))

    # -- Evidence extraction + intersection (CreateAllCindCandidates.scala:106-121,
    # IntersectCindCandidates.scala:14-51): refset(dep) = ∩ over lines of the line's
    # capture set; depCount = number of lines containing dep.
    dep_count = Counter()
    refsets = {}
    for line in join_lines.values():
        for dep in line:
            dep_count[dep] += 1
            if dep in refsets:
                refsets[dep] &= line
            else:
                refsets[dep] = set(line)

    out = set()
    for dep, refs in refsets.items():
        support = dep_count[dep]
        if support < min_support:
            continue
        for ref in refs:
            if _implies(dep, ref):
                continue
            if rules and cc.is_unary(dep[0]) and cc.is_unary(ref[0]) \
                    and cc.secondary(dep[0]) == cc.secondary(ref[0]) \
                    and (cc.primary(dep[0]), cc.primary(ref[0]),
                         dep[1], ref[1]) in rules:
                continue
            out.add((*dep, *ref, support))
    return out


def minimize_cinds(cinds):
    """Remove implied CINDs (the reference's --clean-implied pass).

    Reference: TraversalStrategy.scala:126-168 with RemoveNonMinimalDoubleXxxCinds /
    RemoveNonMinimalXxxSingleCinds.  Note the reference's documented limitation: only
    direct implications are checked (a 2/1 implied by a 1/2 without the corresponding
    1/1 or 2/2 survives), and ALL 1/2 CINDs are kept.  Input/output: sets of 7-tuples.
    """
    def fam(c):
        dep_bin = cc.is_binary(c[0])
        ref_bin = cc.is_binary(c[3])
        return (2 if dep_bin else 1, 2 if ref_bin else 1)

    c11 = {c for c in cinds if fam(c) == (1, 1)}
    c12 = {c for c in cinds if fam(c) == (1, 2)}
    c21 = {c for c in cinds if fam(c) == (2, 1)}
    c22 = {c for c in cinds if fam(c) == (2, 2)}

    def dep_subcaptures(c):
        code, v1, v2 = c[0], c[1], c[2]
        return ((cc.first_subcapture(code), v1, NO_VALUE),
                (cc.second_subcapture(code), v2, NO_VALUE))

    def ref_subcaptures(c):
        code, v1, v2 = c[3], c[4], c[5]
        return ((cc.first_subcapture(code), v1, NO_VALUE),
                (cc.second_subcapture(code), v2, NO_VALUE))

    # 2/1 implied by 1/1: same ref, 1/1's dep is a subcapture of the 2/1's dep.
    implying = {((c[3], c[4], c[5]), (c[0], c[1], c[2])) for c in c11}
    m21 = {c for c in c21
           if not any(((c[3], c[4], c[5]), sub) in implying for sub in dep_subcaptures(c))}
    # ... and 2/1 implied by 2/2: same dep, 2/1's ref is a subcapture of the 2/2's ref.
    implying = {((c[0], c[1], c[2]), sub) for c in c22 for sub in ref_subcaptures(c)}
    m21 = {c for c in m21 if ((c[0], c[1], c[2]), (c[3], c[4], c[5])) not in implying}

    # 1/1 implied by 1/2: same dep, 1/1's ref is a subcapture of the 1/2's ref.
    implying = {((c[0], c[1], c[2]), sub) for c in c12 for sub in ref_subcaptures(c)}
    m11 = {c for c in c11 if ((c[0], c[1], c[2]), (c[3], c[4], c[5])) not in implying}

    # 2/2 implied by 1/2: same ref, 1/2's dep is a subcapture of the 2/2's dep.
    implying = {((c[3], c[4], c[5]), (c[0], c[1], c[2])) for c in c12}
    m22 = {c for c in c22
           if not any(((c[3], c[4], c[5]), sub) in implying for sub in dep_subcaptures(c))}

    return m11 | m21 | c12 | m22
