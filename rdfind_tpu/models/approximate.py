"""ApproximateAllAtOnce traversal strategy (the reference's id 2).

Two rounds, exact end-to-end (plan/ApproximateAllAtOnceTraversalStrategy.scala:
27-114):

  round 1 — instead of materializing every co-occurrence pair (AllAtOnce), build a
      fixed-width Bloom **refset sketch per dependent capture**: OR the hash bits of
      each join line's captures into a line Bloom, then AND the line Blooms over
      every line containing the dependent (ops/sketch.py).  The AND of Blooms is a
      conservative superset of the Bloom of the exact refset intersection — the
      same guarantee the reference gets from BloomFilter.intersect
      (IntersectHalfApproximateCindCandidates.scala:40-44).
  candidate generation — "which captures r could be in dep d's refset" is answered
      for all (d, r) at once by the bitset-containment **matmul on the MXU**
      (sketch.contains_matrix), tiled over dependents.
  round 2 — exact verification by co-occurrence counting restricted to candidate
      pairs: rows whose capture is neither a candidate dep nor a candidate ref are
      dropped before the quadratic pair emission, surviving pairs are semi-joined
      against the candidate set, and the CIND test cooc(d, r) == |d| runs on exact
      counts (mirrors the re-evaluation round, CreateApproximatedCindCandidates
      .scala:59-163, without its small-join-line skip: counting needs every line).

Design difference vs. the reference, on purpose: the reference keeps small refsets
exact in round 1 and sketches only those above `--exactness-threshold`; here row-1
state is one fixed-shape sketch matrix for ALL dependents (num_caps × bits), which
is the TPU-friendly layout (static shapes, scatter/matmul, no per-evidence variable
width).  False positives cost only round-2 verification work, never correctness, so
raw output is identical to raw AllAtOnce (differential-tested).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..data import CindTable
from ..obs import integrity, metrics
from ..ops import cooc as cooc_ops
from ..ops import frequency, minimality, segments, sketch
from ..runtime import dispatch
from . import allatonce, small_to_large

DEP_TILE = 1 << 12


def _build_sketches(line_val_h, line_cap_h, num_caps, *, bits, num_hashes,
                    row_budget=sketch.BUILD_ROW_BUDGET):
    """Packed (cap_pad, bits//32) refset sketches, RESIDENT ON DEVICE.

    Rows arrive sorted by (join value, capture).  Line Blooms are built per
    line-aligned chunk; dependent sketches are AND-accumulated across chunks
    on device (sketch.intersect_dep_sketches_acc) — nothing crosses the
    tunnel during the build (r4 pulled every partial sketch matrix to host
    and ANDed in numpy; VERDICT's first strategy-2 bottleneck).  cap_pad
    follows the cooc padding policy (tile-multiple by default — the
    containment matmul then issues almost no padding rows — pow2-bucketed
    under RDFIND_TILE_SCHEDULE=0); padded captures keep the all-ones
    empty-AND sketch and are masked out by _candidate_pairs' dep/ref masks.
    """
    n = line_val_h.shape[0]
    starts = np.empty(n, bool)
    starts[0] = True
    starts[1:] = line_val_h[1:] != line_val_h[:-1]
    line_gid = np.cumsum(starts, dtype=np.int64) - 1
    line_start_rows = np.flatnonzero(starts)
    num_lines = len(line_start_rows)

    cap_pad = cooc_ops.cap_pad(num_caps)
    sketches = jnp.full((cap_pad, bits // 32), 0xFFFFFFFF, jnp.uint32)
    # Chunk over whole lines so each line's Bloom is complete within its chunk.
    chunk_first_line = 0
    while chunk_first_line < num_lines:
        last = chunk_first_line
        rs = int(line_start_rows[chunk_first_line])
        while last < num_lines:
            re = (int(line_start_rows[last + 1]) if last + 1 < num_lines else n)
            if re - rs > row_budget and last > chunk_first_line:
                break
            last += 1
        re = int(line_start_rows[last]) if last < num_lines else n
        rows = slice(rs, re)
        m = re - rs
        row_cap = segments.pow2_capacity(m)
        lines_cap = segments.pow2_capacity(last - chunk_first_line)
        pad = allatonce._pad_np
        gid_local = (line_gid[rows] - chunk_first_line).astype(np.int32)
        cap_local = line_cap_h[rows]
        valid = jnp.arange(row_cap, dtype=jnp.int32) < m
        gid_d = jnp.asarray(pad(gid_local, row_cap, 0))
        cap_d = jnp.asarray(pad(cap_local, row_cap, 0))
        blooms = sketch.build_line_blooms(
            gid_d, cap_d, valid,
            num_lines=lines_cap, bits=bits, num_hashes=num_hashes)
        sketches = sketch.intersect_dep_sketches_acc(
            sketches, cap_d, blooms[gid_d], valid)
        chunk_first_line = last
    return sketches


@functools.partial(jax.jit, static_argnames=("tile", "bits", "num_hashes"))
def _stage_cand_tile(sketches, lo, dep_ok, ref_ok, ref_ids, ref_pack, *,
                     tile: int, bits: int, num_hashes: int):
    """One (tile x cap_pad) candidate block, bit-packed along refs.

    Slices the device-resident sketch matrix, runs the containment matmul,
    applies the dep/ref masks and the no-self-pair diagonal, and packs —
    the host never sees the bool matrix, only the decoded index pairs
    (the strategy-0 decode discipline, ops/cooc.py).
    """
    tile_sk = jax.lax.dynamic_slice(sketches, (lo, 0),
                                    (tile, sketches.shape[1]))
    cand = sketch.contains_matrix(tile_sk, ref_ids, ref_ok, bits=bits,
                                  num_hashes=num_hashes, ref_pack=ref_pack)
    d_idx = lo + jnp.arange(tile, dtype=jnp.int32)
    cand &= dep_ok[d_idx][:, None]
    cand &= d_idx[:, None] != ref_ids[None, :]
    return cooc_ops.pack_bool(cand)


def _candidate_pairs(sketches, num_caps, *, bits, num_hashes,
                     dep_mask=None, ref_mask=None, dep_tile=DEP_TILE):
    """All (dep, ref) capture-id pairs passing the sketch test, dep != ref.

    Tiled over dependents; each tile is one MXU containment matmul whose
    masked output is bit-packed on device and decoded by popcount + sized
    nonzero (cooc_ops.extract_packed), so only the candidate index pairs
    travel to the host — never the (tile x caps) bool matrix (r4 pulled the
    full uint8 matrix per tile; VERDICT's second strategy-2 bottleneck).
    Optional dep_mask/ref_mask restrict either side (the LateBB rounds).
    """
    cap_pad = sketches.shape[0]
    # Tile width must divide cap_pad: a clamped dynamic_slice start would
    # silently recompute earlier dep rows and mislabel their indices.
    tile = cooc_ops.tile_for(cap_pad, dep_tile)
    ref_ids = jnp.arange(cap_pad, dtype=jnp.int32)
    ref_ok_h = np.zeros(cap_pad, bool)
    ref_ok_h[:num_caps] = True if ref_mask is None else ref_mask[:num_caps]
    ref_ok = jnp.asarray(ref_ok_h)
    dep_ok_h = np.zeros(cap_pad, bool)
    dep_ok_h[:num_caps] = True if dep_mask is None else dep_mask[:num_caps]
    dep_ok = jnp.asarray(dep_ok_h)
    # Pack the shared ref side once; every dep tile reuses it (pallas backend).
    ref_pack = (sketch.pack_ref_bits(ref_ids, bits=bits, num_hashes=num_hashes)
                if sketch.pallas_eligible(bits) else None)
    los = [lo for lo in range(0, num_caps, tile)
           if dep_mask is None or dep_mask[lo:min(lo + tile, num_caps)].any()]

    def make(lo):
        return lambda: (_stage_cand_tile(sketches, jnp.int32(lo), dep_ok,
                                         ref_ok, ref_ids, ref_pack, tile=tile,
                                         bits=bits, num_hashes=num_hashes),
                        min(num_caps - lo, tile), num_caps)

    pairs = cooc_ops.extract_packed_iter([make(lo) for lo in los],
                                         tile * cap_pad)
    out_d = [d + lo for lo, (d, _) in zip(los, pairs) if d.size]
    out_r = [r for _, (d, r) in zip(los, pairs) if d.size]
    if not out_d:
        z = np.zeros(0, np.int64)
        return z, z
    return np.concatenate(out_d), np.concatenate(out_r)


@functools.partial(jax.jit, static_argnames=("tile",))
def _stage_tile_counts(m, dep_lo, d_local, r_idx, valid, *, tile: int):
    """Exact co-occurrence counts for candidate pairs inside one dep tile.

    m: (l_pad, c_pad) bf16 membership matrix; one (tile x c_pad) MXU matmul
    computes the tile's cooc block, then the candidate (dep, ref) positions are
    gathered on device — only the per-pair counts travel back to the host.
    """
    m_tile = jax.lax.dynamic_slice(m, (0, dep_lo), (m.shape[0], tile))
    cooc = cooc_ops.cooc_dot(m_tile, m)
    return jnp.where(valid, cooc[d_local, r_idx], 0)


def _dense_verify_counts(line_val_h, line_cap_h, num_caps, cand_dep, cand_ref,
                         dep_ok, ref_ok, stats, stat_key):
    """Round-2 verification on the dense MXU path: exact cooc counts for the
    candidate pairs, or None when the membership matrix exceeds the HBM budget
    (caller falls back to the chunked loop).

    Same row filter as the chunked backend (_iter_chunk_pairs): rows flagged
    for neither side belong to captures in no candidate pair, so dropping them
    cannot change any candidate's count.  Replaces the per-chunk host loop of
    CreateApproximatedCindCandidates.scala:59-163 with one membership scatter
    plus a tiled matmul sweep — the same stage AllAtOnce verifies with, here
    restricted to the sketch survivors.
    """
    row_keep = dep_ok[line_cap_h] | ref_ok[line_cap_h]
    lv, lc = line_val_h[row_keep], line_cap_h[row_keep]
    n = lv.shape[0]
    if n == 0:
        return np.zeros(len(cand_dep), np.int64)
    starts = np.empty(n, bool)
    starts[0] = True
    starts[1:] = lv[1:] != lv[:-1]
    line_gid = np.cumsum(starts, dtype=np.int64) - 1
    num_lines = int(line_gid[-1]) + 1
    plan = cooc_ops.dense_plan(num_lines, num_caps)
    if plan is None or plan.c_pad > allatonce.SINGLE_SHOT_C:
        return None
    l_pad, c_pad, tile = plan.l_pad, plan.c_pad, plan.tile
    if stats is not None:
        lens = np.diff(np.append(np.flatnonzero(starts), n)).astype(np.int64)
        tot = int((lens * (lens - 1)).sum())
        metrics.counter_add(stats, stat_key, tot)
        metrics.counter_add(stats, "total_pairs", tot)
        metrics.struct_set(stats, "dense_plan", plan.describe())
        metrics.gauge_set(stats, "cooc_dtype", plan.dtype)
        metrics.gauge_set(stats, "plane_bits", plan.plane_bits)
        metrics.struct_set(stats, "kernel_resolution",
                           cooc_ops.resolution_report())

    row_cap = segments.pow2_capacity(n)
    pad = allatonce._pad_np
    m = cooc_ops.build_membership(
        jnp.asarray(pad(line_gid.astype(np.int32), row_cap, l_pad)),
        jnp.asarray(pad(lc.astype(np.int32), row_cap, c_pad)),
        jnp.arange(row_cap, dtype=jnp.int32) < n, l_pad=l_pad, c_pad=c_pad,
        dtype=plan.dtype)

    # Candidates grouped by dep tile (defensive sort: _candidate_pairs emits
    # dep-ascending, but the contract here is order-insensitive).  All tile
    # gathers are dispatched first and pulled in ONE device_get — per-tile
    # pulls cost one host round trip each over the tunnel (r5).
    order = np.argsort(cand_dep, kind="stable")
    d_sorted, r_sorted = cand_dep[order], cand_ref[order]
    cnt_sorted = np.zeros(len(cand_dep), np.int64)
    spans, pulls, pend_bytes = [], [], 0

    def drain():
        nonlocal spans, pulls, pend_bytes
        for (a, b), got in zip(spans, jax.device_get(pulls)):
            cnt_sorted[a:b] = got[:b - a]
        spans, pulls, pend_bytes = [], [], 0

    for lo in plan.dep_tile_starts:
        a = np.searchsorted(d_sorted, lo)
        b = np.searchsorted(d_sorted, lo + tile)
        if a == b:
            continue
        k = b - a
        k_cap = segments.pow2_capacity(k)
        spans.append((a, b))
        pulls.append(_stage_tile_counts(
            m, jnp.int32(lo),
            jnp.asarray(pad((d_sorted[a:b] - lo).astype(np.int32), k_cap, 0)),
            jnp.asarray(pad(r_sorted[a:b].astype(np.int32), k_cap, 0)),
            jnp.arange(k_cap, dtype=jnp.int32) < k, tile=tile))
        # Start the device->host copy the moment the gather is enqueued: the
        # drain's batched device_get then mostly finds the counts already on
        # host while later tiles' matmuls are still computing.
        dispatch.stage_to_host(pulls[-1:])
        # Pending tiles pin padded inputs + outputs on device (~13 bytes per
        # slot); drain under the shared pull budget so huge candidate sets
        # cannot stack GB of buffers next to the near-budget matrix `m`.
        pend_bytes += 13 * k_cap
        if pend_bytes >= cooc_ops.PULL_BYTES_BUDGET:
            drain()
    drain()
    cnt = np.empty_like(cnt_sorted)
    cnt[order] = cnt_sorted
    return cnt


def _record_backend(stats, stat_key, backend):
    """Per-call backend attribution + a run-level scalar ("mixed" when a
    multi-round strategy's rounds land on different backends)."""
    if stats is None:
        return
    metrics.gauge_set(stats, stat_key + "_backend", backend)
    prev = stats.get("pair_backend")
    metrics.gauge_set(stats, "pair_backend",
                      backend if prev in (None, backend) else "mixed")


def verify_candidates(st, cand_dep, cand_ref, min_support, *, pair_backend,
                      pair_chunk_budget, stats, stat_key):
    """Exact verification of candidate (dep, ref) pairs: (d, r, sup) arrays.

    Backend dispatch shared by the approximate and LateBB strategies: the
    dense membership-matmul gather when the plan fits ("auto"/"matmul"),
    otherwise the chunked host loop via _verify_level.
    """
    if len(cand_dep) == 0:
        # No candidates: no pair phase runs on either backend.
        z = np.zeros(0, np.int64)
        return z, z, z
    cnt = None
    if pair_backend in ("auto", "matmul"):
        dep_ok = np.zeros(st["num_caps"], bool)
        dep_ok[cand_dep] = True
        ref_ok = np.zeros(st["num_caps"], bool)
        ref_ok[cand_ref] = True
        cnt = _dense_verify_counts(
            st["line_val_h"], st["line_cap_h"], st["num_caps"],
            cand_dep, cand_ref, dep_ok, ref_ok, stats, stat_key)
        if cnt is None and pair_backend == "matmul":
            raise ValueError("pair_backend='matmul' but the dense plan "
                             "does not fit the single-shot budget")
    if cnt is not None:
        _record_backend(stats, stat_key, "matmul")
        sup_all = st["dep_count"][cand_dep]
        is_cind = (cnt == sup_all) & (sup_all >= min_support)
        is_cind &= ~small_to_large._implied_mask(
            cand_dep, cand_ref, st["cap_code"], st["cap_v1"], st["cap_v2"])
        return cand_dep[is_cind], cand_ref[is_cind], sup_all[is_cind]

    _record_backend(stats, stat_key, "chunked")

    def cooc_fn(dep_ok, ref_ok, key):
        return small_to_large._chunked_cooc(
            st["line_val_h"], st["line_cap_h"], dep_ok, ref_ok,
            pair_chunk_budget, stats, key)

    return small_to_large._verify_level(
        cooc_fn, cand_dep, cand_ref, st["num_caps"], st["dep_count"],
        st["cap_code"], st["cap_v1"], st["cap_v2"], min_support, stat_key)


# Shared phase A lives with the staging code it drives.
prepare_join_lines = allatonce.prepare_join_lines


def discover(triples, min_support: int, projections: str = "spo",
             use_frequent_condition_filter: bool = True,
             use_association_rules: bool = False,
             clean_implied: bool = False,
             pair_chunk_budget: int = allatonce.PAIR_CHUNK_BUDGET,
             sketch_bits: int = sketch.DEFAULT_BITS,
             sketch_hashes: int = sketch.DEFAULT_HASHES,
             pair_backend: str = "auto",
             stats: dict | None = None) -> CindTable:
    """Discover all CINDs; raw output equals allatonce.discover's raw output.

    pair_backend selects the round-2 verification: "matmul" gathers exact
    counts from the dense membership matmul (requires the dense plan to fit),
    "chunked" runs the legacy host chunk loop, "auto" (default) picks matmul
    whenever the membership matrix fits the HBM budget.  Round 1 (the sketch
    build and the candidate containment matmul) is backend-independent.
    """
    if pair_backend not in ("auto", "matmul", "chunked"):
        raise ValueError(f"unknown pair_backend {pair_backend!r}")
    min_support = max(int(min_support), 1)
    use_ars = use_association_rules and use_frequent_condition_filter
    st = prepare_join_lines(triples, min_support, projections,
                            use_frequent_condition_filter, use_ars, stats)
    if st is None:
        return CindTable.empty()

    sketches = _build_sketches(st["line_val_h"], st["line_cap_h"],
                               st["num_caps"], bits=sketch_bits,
                               num_hashes=sketch_hashes)
    # Infrequent captures were row-filtered out of the join lines: their sketches
    # stay all-ones (empty AND) and they can appear in no CIND on either side —
    # mask them out of candidate generation entirely.
    frequent = st["dep_count"] >= min_support
    cand_dep, cand_ref = _candidate_pairs(sketches, st["num_caps"],
                                          bits=sketch_bits,
                                          num_hashes=sketch_hashes,
                                          dep_mask=frequent, ref_mask=frequent)
    if stats is not None:
        metrics.gauge_set(stats, "n_sketch_candidates", len(cand_dep))
    # The sketch matrix is dead past candidate generation; drop the reference
    # so its HBM is free for round 2's membership matrix.
    del sketches

    d, r, sup = verify_candidates(
        st, cand_dep, cand_ref, min_support, pair_backend=pair_backend,
        pair_chunk_budget=pair_chunk_budget, stats=stats,
        stat_key="pairs_verify")

    cap_code, cap_v1, cap_v2 = st["cap_code"], st["cap_v1"], st["cap_v2"]
    table = CindTable(
        dep_code=cap_code[d], dep_v1=cap_v1[d], dep_v2=cap_v2[d],
        ref_code=cap_code[r], ref_v1=cap_v1[r], ref_v2=cap_v2[r],
        support=sup)
    if use_ars:
        rules = frequency.mine_association_rules(st["triples"], min_support)
        if stats is not None:
            metrics.struct_set(stats, "association_rules", rules)
        table = allatonce.filter_ar_implied_cinds(table, rules)
    if clean_implied:
        table = minimality.minimize_table(table)
    integrity.publish_output(stats, table)
    return table
