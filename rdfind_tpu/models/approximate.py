"""ApproximateAllAtOnce traversal strategy (the reference's id 2).

Two rounds, exact end-to-end (plan/ApproximateAllAtOnceTraversalStrategy.scala:
27-114):

  round 1 — instead of materializing every co-occurrence pair (AllAtOnce), build a
      fixed-width Bloom **refset sketch per dependent capture**: OR the hash bits of
      each join line's captures into a line Bloom, then AND the line Blooms over
      every line containing the dependent (ops/sketch.py).  The AND of Blooms is a
      conservative superset of the Bloom of the exact refset intersection — the
      same guarantee the reference gets from BloomFilter.intersect
      (IntersectHalfApproximateCindCandidates.scala:40-44).
  candidate generation — "which captures r could be in dep d's refset" is answered
      for all (d, r) at once by the bitset-containment **matmul on the MXU**
      (sketch.contains_matrix), tiled over dependents.
  round 2 — exact verification by co-occurrence counting restricted to candidate
      pairs: rows whose capture is neither a candidate dep nor a candidate ref are
      dropped before the quadratic pair emission, surviving pairs are semi-joined
      against the candidate set, and the CIND test cooc(d, r) == |d| runs on exact
      counts (mirrors the re-evaluation round, CreateApproximatedCindCandidates
      .scala:59-163, without its small-join-line skip: counting needs every line).

Design difference vs. the reference, on purpose: the reference keeps small refsets
exact in round 1 and sketches only those above `--exactness-threshold`; here row-1
state is one fixed-shape sketch matrix for ALL dependents (num_caps × bits), which
is the TPU-friendly layout (static shapes, scatter/matmul, no per-evidence variable
width).  False positives cost only round-2 verification work, never correctness, so
raw output is identical to raw AllAtOnce (differential-tested).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..data import CindTable
from ..ops import frequency, minimality, segments, sketch
from . import allatonce, small_to_large

DEP_TILE = 1 << 12


def _build_sketches(line_val_h, line_cap_h, num_caps, *, bits, num_hashes,
                    row_budget=sketch.BUILD_ROW_BUDGET):
    """Packed (num_caps, bits//32) refset sketches from host join-line rows.

    Rows arrive sorted by (join value, capture).  Line Blooms are built per
    line-aligned chunk; dependent sketches are AND-accumulated across chunks (a
    capture's rows may span chunks), packed-AND on host between device stages.
    """
    n = line_val_h.shape[0]
    starts = np.empty(n, bool)
    starts[0] = True
    starts[1:] = line_val_h[1:] != line_val_h[:-1]
    line_gid = np.cumsum(starts, dtype=np.int64) - 1
    line_start_rows = np.flatnonzero(starts)
    num_lines = len(line_start_rows)

    sketches = np.full((num_caps, bits // 32), 0xFFFFFFFF, np.uint32)
    # Chunk over whole lines so each line's Bloom is complete within its chunk.
    chunk_first_line = 0
    while chunk_first_line < num_lines:
        last = chunk_first_line
        rs = int(line_start_rows[chunk_first_line])
        while last < num_lines:
            re = (int(line_start_rows[last + 1]) if last + 1 < num_lines else n)
            if re - rs > row_budget and last > chunk_first_line:
                break
            last += 1
        re = int(line_start_rows[last]) if last < num_lines else n
        rows = slice(rs, re)
        m = re - rs
        row_cap = segments.pow2_capacity(m)
        lines_cap = segments.pow2_capacity(last - chunk_first_line)
        pad = allatonce._pad_np
        gid_local = (line_gid[rows] - chunk_first_line).astype(np.int32)
        cap_local = line_cap_h[rows]
        valid = jnp.arange(row_cap, dtype=jnp.int32) < m
        blooms = sketch.build_line_blooms(
            jnp.asarray(pad(gid_local, row_cap, 0)),
            jnp.asarray(pad(cap_local, row_cap, 0)), valid,
            num_lines=lines_cap, bits=bits, num_hashes=num_hashes)
        part = sketch.intersect_dep_sketches(
            jnp.asarray(pad(cap_local, row_cap, 0)),
            blooms[jnp.asarray(pad(gid_local, row_cap, 0))], valid,
            num_caps=num_caps, bits=bits)
        sketches &= np.asarray(part)
        chunk_first_line = last
    return sketches


def _candidate_pairs(sketches, num_caps, *, bits, num_hashes,
                     dep_mask=None, ref_mask=None, dep_tile=DEP_TILE):
    """All (dep, ref) capture-id pairs passing the sketch test, dep != ref.

    Tiled over dependents; each tile is one MXU containment matmul.  Optional
    dep_mask/ref_mask restrict either side (used by the LateBB rounds).
    """
    # Pad both sides to bucketed capacities so contains_matrix compiles once per
    # (tile, ref_cap) bucket instead of once per dataset (pow2 capacity policy).
    ref_cap = segments.pow2_capacity(num_caps)
    ref_ids = jnp.arange(ref_cap, dtype=jnp.int32)
    ref_ok_h = np.zeros(ref_cap, bool)
    ref_ok_h[:num_caps] = True if ref_mask is None else ref_mask[:num_caps]
    ref_ok = jnp.asarray(ref_ok_h)
    # Pack the shared ref side once; every dep tile reuses it (pallas backend).
    ref_pack = (sketch.pack_ref_bits(ref_ids, bits=bits, num_hashes=num_hashes)
                if sketch.pallas_eligible(bits) else None)
    out_d, out_r = [], []
    for lo in range(0, num_caps, dep_tile):
        hi = min(lo + dep_tile, num_caps)
        if dep_mask is not None and not dep_mask[lo:hi].any():
            continue
        tile_h = sketches[lo:hi]
        if tile_h.shape[0] < dep_tile:
            tile_h = np.concatenate([tile_h, np.zeros(
                (dep_tile - tile_h.shape[0], tile_h.shape[1]), tile_h.dtype)])
        cand = np.array(sketch.contains_matrix(
            jnp.asarray(tile_h), ref_ids, ref_ok, bits=bits,
            num_hashes=num_hashes, ref_pack=ref_pack))[:hi - lo, :num_caps]
        if dep_mask is not None:
            cand &= dep_mask[lo:hi, None]
        d, r = np.nonzero(cand)
        d = d.astype(np.int64) + lo
        r = r.astype(np.int64)
        keep = d != r
        out_d.append(d[keep])
        out_r.append(r[keep])
    if not out_d:
        z = np.zeros(0, np.int64)
        return z, z
    return np.concatenate(out_d), np.concatenate(out_r)


# Shared phase A lives with the staging code it drives.
prepare_join_lines = allatonce.prepare_join_lines


def discover(triples, min_support: int, projections: str = "spo",
             use_frequent_condition_filter: bool = True,
             use_association_rules: bool = False,
             clean_implied: bool = False,
             pair_chunk_budget: int = allatonce.PAIR_CHUNK_BUDGET,
             sketch_bits: int = sketch.DEFAULT_BITS,
             sketch_hashes: int = sketch.DEFAULT_HASHES,
             stats: dict | None = None) -> CindTable:
    """Discover all CINDs; raw output equals allatonce.discover's raw output."""
    min_support = max(int(min_support), 1)
    use_ars = use_association_rules and use_frequent_condition_filter
    st = prepare_join_lines(triples, min_support, projections,
                            use_frequent_condition_filter, use_ars, stats)
    if st is None:
        return CindTable.empty()

    sketches = _build_sketches(st["line_val_h"], st["line_cap_h"],
                               st["num_caps"], bits=sketch_bits,
                               num_hashes=sketch_hashes)
    # Infrequent captures were row-filtered out of the join lines: their sketches
    # stay all-ones (empty AND) and they can appear in no CIND on either side —
    # mask them out of candidate generation entirely.
    frequent = st["dep_count"] >= min_support
    cand_dep, cand_ref = _candidate_pairs(sketches, st["num_caps"],
                                          bits=sketch_bits,
                                          num_hashes=sketch_hashes,
                                          dep_mask=frequent, ref_mask=frequent)
    if stats is not None:
        stats["n_sketch_candidates"] = len(cand_dep)

    def cooc_fn(dep_ok, ref_ok, stat_key):
        return small_to_large._chunked_cooc(
            st["line_val_h"], st["line_cap_h"], dep_ok, ref_ok,
            pair_chunk_budget, stats, stat_key)

    d, r, sup = small_to_large._verify_level(
        cooc_fn, cand_dep, cand_ref, st["num_caps"], st["dep_count"],
        st["cap_code"], st["cap_v1"], st["cap_v2"], min_support, "pairs_verify")

    cap_code, cap_v1, cap_v2 = st["cap_code"], st["cap_v1"], st["cap_v2"]
    table = CindTable(
        dep_code=cap_code[d], dep_v1=cap_v1[d], dep_v2=cap_v2[d],
        ref_code=cap_code[r], ref_v1=cap_v1[r], ref_v2=cap_v2[r],
        support=sup)
    if use_ars:
        rules = frequency.mine_association_rules(st["triples"], min_support)
        if stats is not None:
            stats["association_rules"] = rules
        table = allatonce.filter_ar_implied_cinds(table, rules)
    if clean_implied:
        table = minimality.minimize_table(table)
    return table
