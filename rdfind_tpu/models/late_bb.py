"""LateBB traversal strategy (the reference's id 3).

Two rounds over the join lines (plan/LateBBTraversalStrategy.scala:24-123):

  round 1 — **unary dependents only**: build the per-dependent Bloom refset
      sketches (shared with strategy 2), generate candidate refs for unary deps via
      the MXU containment matmul, verify exactly by co-occurrence counting.  Yields
      every 1/1 and 1/2 CIND (the reference's half-approximate
      CreateAlmostAllHalfApproximateCindCandidates round, with our count-based
      verification replacing its round-2 re-check — exact in one pass here).
  round 2 — **binary dependents**, pruned by round 1's knowledge: a candidate
      (d1∧d2 ⊆ r) whose value-matching unary subcapture already satisfies
      (d1 ⊆ r) is implied and skipped before verification (the known-CIND pruning
      of CreateApproximatedCindCandidates2.scala:151-170; its negative-count
      "already counted" marker is unnecessary here because counting is one-shot).

Raw output = raw AllAtOnce minus the non-minimal 2/x CINDs implied by a 1/x CIND
on a value-substituted dep subcapture; with clean_implied both are the identical
minimal set (differential-tested).  Association rules filter the final table only
(same pairs AllAtOnce filters), not the round-1 prune set, so higher-family output
never depends on AR pruning — unlike S2L's inherited AR-before-generation quirk.
"""

from __future__ import annotations

import numpy as np

from .. import conditions as cc
from ..data import CindTable
from ..obs import integrity, metrics
from ..ops import frequency, minimality, sketch
from . import allatonce, approximate, small_to_large


def discover(triples, min_support: int, projections: str = "spo",
             use_frequent_condition_filter: bool = True,
             use_association_rules: bool = False,
             clean_implied: bool = False,
             pair_chunk_budget: int = allatonce.PAIR_CHUNK_BUDGET,
             sketch_bits: int = sketch.DEFAULT_BITS,
             sketch_hashes: int = sketch.DEFAULT_HASHES,
             pair_backend: str = "auto",
             stats: dict | None = None) -> CindTable:
    """Discover CINDs in two rounds: unary dependents first, binary pruned after.

    pair_backend selects each round's exact verification (see
    approximate.discover): "matmul" = dense membership-matmul gather,
    "chunked" = legacy host loop, "auto" = matmul when it fits.
    """
    if pair_backend not in ("auto", "matmul", "chunked"):
        raise ValueError(f"unknown pair_backend {pair_backend!r}")
    min_support = max(int(min_support), 1)
    use_ars = use_association_rules and use_frequent_condition_filter
    st = approximate.prepare_join_lines(triples, min_support, projections,
                                        use_frequent_condition_filter, use_ars,
                                        stats)
    if st is None:
        return CindTable.empty()
    cap_code, cap_v1, cap_v2 = st["cap_code"], st["cap_v1"], st["cap_v2"]
    num_caps, dep_count = st["num_caps"], st["dep_count"]
    unary = np.asarray(cc.is_unary(cap_code))

    sketches = approximate._build_sketches(
        st["line_val_h"], st["line_cap_h"], num_caps,
        bits=sketch_bits, num_hashes=sketch_hashes)

    # ONE containment pass for all frequent captures (the MXU matmul is the
    # dominant cost — don't run it once per round), split by dep arity after.
    frequent = dep_count >= min_support
    cand_dep, cand_ref = approximate._candidate_pairs(
        sketches, num_caps, bits=sketch_bits, num_hashes=sketch_hashes,
        dep_mask=frequent, ref_mask=frequent)
    # Dead past candidate generation; free its HBM before the verify rounds.
    del sketches
    dep_is_unary = unary[cand_dep]

    # Round 1: unary dependents, refs of both arities.
    c1_dep, c1_ref = cand_dep[dep_is_unary], cand_ref[dep_is_unary]
    d1, r1, sup1 = approximate.verify_candidates(
        st, c1_dep, c1_ref, min_support, pair_backend=pair_backend,
        pair_chunk_budget=pair_chunk_budget, stats=stats,
        stat_key="pairs_round1")
    if stats is not None:
        metrics.set_many(stats, n_round1_candidates=len(c1_dep),
                         n_round1_cinds=len(d1))

    # Round 2: binary dependents, candidates pruned by round-1 CINDs — a
    # candidate (d1^d2, r) with a known value-matching (d1, r) CIND is implied
    # (same subcapture probe as S2L's 2/2-vs-1/2 prune, which is family-generic).
    c2_dep, c2_ref = cand_dep[~dep_is_unary], cand_ref[~dep_is_unary]
    keep = small_to_large._prune_22_vs_12(c2_dep, c2_ref, d1, r1,
                                          cap_code, cap_v1, cap_v2)
    c2_dep, c2_ref = c2_dep[keep], c2_ref[keep]
    d2, r2, sup2 = approximate.verify_candidates(
        st, c2_dep, c2_ref, min_support, pair_backend=pair_backend,
        pair_chunk_budget=pair_chunk_budget, stats=stats,
        stat_key="pairs_round2")
    if stats is not None:
        metrics.set_many(stats, n_round2_candidates=len(c2_dep),
                         n_round2_cinds=len(d2))

    all_d = np.concatenate([d1, d2])
    all_r = np.concatenate([r1, r2])
    all_s = np.concatenate([sup1, sup2])
    table = CindTable(
        dep_code=cap_code[all_d], dep_v1=cap_v1[all_d], dep_v2=cap_v2[all_d],
        ref_code=cap_code[all_r], ref_v1=cap_v1[all_r], ref_v2=cap_v2[all_r],
        support=all_s)
    if use_ars:
        rules = frequency.mine_association_rules(st["triples"], min_support)
        if stats is not None:
            metrics.struct_set(stats, "association_rules", rules)
        table = allatonce.filter_ar_implied_cinds(table, rules)
    if clean_implied:
        table = minimality.minimize_table(table)
    integrity.publish_output(stats, table)
    return table
